"""JAX/XLA device kernels — the TPU execution path.

Design (SURVEY.md §7 step 6 + hard parts):
- **Static shapes**: aggregation uses sort + segment_sum with a padded
  group capacity; joins are two-pass (count on device, host reads the total,
  expansion kernel with a static output size). This is the standard answer
  to XLA's no-dynamic-shapes rule.
- **Fusion**: a whole scan→filter→project→aggregate pipeline compiles into
  ONE jitted program, so lineitem never round-trips to the host between
  operators (the coprocessor-pushdown boundary of the reference becomes the
  host↔device boundary).
- **Exactness**: decimals stay scaled int64 end-to-end (x64 enabled);
  sums are exact; decimal division uses round-half-away integer math.
- **Strings**: dictionary codes (int32) computed host-side; equality /
  IN constants are translated to codes before tracing.

reference parity: executor/aggregate.go (hash agg) → sort-based segment
aggregation; executor/join.go + hash_table.go → sort + searchsorted join;
expression/*_vec.go → compile_expr tracing numpy-identical semantics.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

from ..errors import TiDBError
from ..expression.core import (
    Column as ExprColumn, Constant, ScalarFunc, phys_kind,
    K_DATE, K_DEC, K_FLOAT, K_INT, K_STR,
)
from ..sqltypes import POW10, TYPE_DATETIME, TYPE_TIMESTAMP


class DeviceUnsupported(TiDBError):
    """Raised during compilation when an expression/type can't run on
    device; the executor falls back to the host kernels."""


def next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return max(p, 8)


# ---------------------------------------------------------------------------
# shape canonicalization: geometric row buckets
# ---------------------------------------------------------------------------
#
# XLA programs are compiled per SHAPE: tracing against the exact row count
# means any delta append, different table, or different scale factor forces a
# full recompile — the dominant cost on a remote device (BENCH_TPU_LIVE: Q3
# spent 378s compiling for 45s of compute). Device arrays are therefore
# padded up to a small set of geometric buckets (`bucket_rows`), with the
# live row count threaded through the jitted program as a TRACED scalar:
# padding rows carry null=True and are masked by `arange(n) < n_live` before
# any filter/join/aggregate, extending the existing "padding must not
# survive the scan filter" invariant of the paged path. A within-bucket
# delta then re-dispatches the already-compiled program.

import math as _math


def bucket_rows(n: int, per_double: int = 2) -> int:
    """Smallest geometric bucket >= n: `per_double` buckets per doubling
    (2 = powers of sqrt(2): 8, 12, 16, 23, 32, 46, 64, ...). per_double <= 0
    disables bucketing (exact shapes). Worst-case padding overhead is
    2^(1/per_double) - 1 (~41% at 1, ~19% at 2)."""
    if per_double <= 0 or n <= 0:
        return n
    b, k = 8, 0
    while b < n:
        k += 1
        b = _math.ceil(2 ** (3 + k / per_double))
    return b


def shape_buckets(ctx) -> int:
    """The session's bucket granularity (sysvar tidb_device_shape_buckets;
    default 2 buckets per doubling, 0 = exact shapes)."""
    try:
        return int(ctx.get_sysvar("tidb_device_shape_buckets"))
    except Exception:
        return 2


def pad_host(arr, n_to: int, null_pad: bool = False):
    """Pad a host array to `n_to` rows. Data pads with zeros (any value is
    fine — padding is masked), null masks pad with True (`null_pad`) so a
    padding row reads as NULL even before the n_live mask applies."""
    arr = np.asarray(arr)
    n = arr.shape[0]
    if n_to <= n:
        return arr
    if null_pad:
        out = np.ones(n_to, dtype=arr.dtype)
    else:
        out = np.zeros(n_to, dtype=arr.dtype)
    out[:n] = arr
    return out


# ---------------------------------------------------------------------------
# column transfer
# ---------------------------------------------------------------------------

class DeviceCol:
    """Device representation of one column: data + null mask (+ dictionary
    for strings; data holds int32 codes)."""

    __slots__ = ("data", "nulls", "dictionary", "reps", "ftype", "host_col")

    def __init__(self, data, nulls, ftype, dictionary=None, reps=None,
                 host_col=None):
        self.data = data
        self.nulls = nulls
        self.ftype = ftype
        # For _ci columns the dictionary holds the sorted collation sort
        # keys (constants are transformed before lookup) and reps holds a
        # representative original value per class for output decode.
        self.dictionary = dictionary
        self.reps = reps
        # backing utils.chunk.Column (when known): host min/max feed static
        # key-range packing in the agg planner (device_exec._key_pack)
        self.host_col = host_col

    def decode_dict(self):
        """The dictionary that maps codes back to OUTPUT strings."""
        return self.reps if self.reps is not None else self.dictionary


def to_device_col(col, bucket: int | None = None) -> DeviceCol:
    """utils.chunk.Column → DeviceCol. Strings are dict-encoded host-side.

    The device arrays are cached on the Column THROUGH the residency
    manager (ops/residency.py): a table's working set is uploaded to HBM
    once per columnar-cache version and reused across queries (the
    transfer — not the kernel — dominates when the device sits across a
    fabric/tunnel), with every cached upload byte-accounted against
    `tidb_device_mem_budget`, LRU-evictable under pressure, and stamped
    with the device epoch so a fenced/restarted backend never serves a
    stale buffer.

    `bucket` (> len) pads the uploaded arrays to that static row count:
    padding rows carry null=True and zeroed data, and the consuming
    pipeline must mask them via its traced live-row count. One padded
    length is cached per column: a LONGER cached upload serves shorter
    requests as a device-side slice (no host re-transfer — an
    exact-shape consumer like the mpp path must not thrash a bucketed
    HBM-resident cache); only a grow evicts and re-uploads."""
    from . import residency
    want = bucket if bucket is not None and bucket > len(col) else len(col)
    cached = residency.lookup(col, want)
    if cached is None:
        # chaos hook: a synthetic RESOURCE_EXHAUSTED at the upload
        # boundary (classified device OOM → run_device's evict-all →
        # retry → host-degradation ladder)
        from ..utils import failpoint
        failpoint.inject("device-upload-oom")
        if col.is_object():
            from ..sqltypes import TYPE_NEWDECIMAL
            if col.ftype.tp == TYPE_NEWDECIMAL:
                # wide decimals (precision > 18) are exact host bigints;
                # dict-encoding them as strings would break arithmetic
                raise DeviceUnsupported("wide-decimal column")
            from ..utils.collate import is_ci
            if is_ci(col.ftype.collate):
                # _ci columns encode as collation-class codes: ranks in
                # sort-key order, so code equality/ordering IS collation
                # semantics (utils/chunk.py dict_encode_ci)
                ci_codes, _kd, _reps = col.dict_encode_ci(col.ftype.collate)
                built = (jnp.asarray(pad_host(ci_codes, want)),
                         jnp.asarray(pad_host(col.nulls, want, True)))
            else:
                codes, _uniq = col.dict_encode()
                built = (jnp.asarray(pad_host(codes, want)),
                         jnp.asarray(pad_host(col.nulls, want, True)))
        else:
            built = (jnp.asarray(pad_host(col.data, want)),
                     jnp.asarray(pad_host(col.nulls, want, True)))
        # compare-and-keep publish under the residency lock: a racing
        # builder's loser arrays are accounted as immediately evicted,
        # never leaked outside the ledger
        cached = residency.publish(col, *built)
    data, nulls = cached
    if int(data.shape[0]) > want:
        # cached at a larger bucket: on-device slice (HBM-local, cheap)
        data, nulls = data[:want], nulls[:want]
    if col.is_object():
        from ..utils.collate import is_ci
        if is_ci(col.ftype.collate):
            _cc, key_dict, reps = col.dict_encode_ci(col.ftype.collate)
            return DeviceCol(data, nulls, col.ftype, dictionary=key_dict,
                             reps=reps, host_col=col)
        _codes, uniq = col.dict_encode()
        return DeviceCol(data, nulls, col.ftype, dictionary=uniq,
                         host_col=col)
    return DeviceCol(data, nulls, col.ftype, host_col=col)


def meta_device_col(col):
    """(DeviceCol with data=None, (host_data, host_nulls)) — the streamed/
    paged protocol: the DeviceCol carries only what the expression compiler
    reads (ftype, dictionaries, host_col for min/max packing); the host
    arrays are sliced into pages and uploaded per block by the caller.
    Never touches device memory, and never materializes a LazyDictColumn's
    object view (codes come straight off the memmap)."""
    if col.is_object():
        from ..sqltypes import TYPE_NEWDECIMAL
        if col.ftype.tp == TYPE_NEWDECIMAL:
            raise DeviceUnsupported("wide-decimal column")
        from ..utils.collate import is_ci
        if is_ci(col.ftype.collate):
            ci_codes, key_dict, reps = col.dict_encode_ci(col.ftype.collate)
            return (DeviceCol(None, None, col.ftype, dictionary=key_dict,
                              reps=reps, host_col=col),
                    (ci_codes, col.nulls))
        codes, uniq = col.dict_encode()
        return (DeviceCol(None, None, col.ftype, dictionary=uniq,
                          host_col=col),
                (codes, col.nulls))
    return (DeviceCol(None, None, col.ftype, host_col=col),
            (col.data, col.nulls))


# ---------------------------------------------------------------------------
# expression → jax compiler
# ---------------------------------------------------------------------------

def compile_expr(expr, cols: dict):
    """Build a traceable fn(env) -> (data, nulls) where env maps column idx
    → (jnp data, jnp nulls). `cols` maps idx → DeviceCol (for dictionaries
    and dtypes at compile time). Raises DeviceUnsupported when out of scope."""
    if isinstance(expr, ExprColumn):
        idx = expr.idx

        def f(env):
            return env[idx]
        return f
    if isinstance(expr, Constant):
        return _compile_const(expr, cols)
    if isinstance(expr, ScalarFunc):
        return _compile_func(expr, cols)
    raise DeviceUnsupported(f"cannot compile {type(expr).__name__} for device")


def _compile_const(expr: Constant, cols):
    """Constants trace as 0-d arrays so they broadcast against whichever
    column they meet — in a multi-table fragment the env holds arrays of
    several lengths, so sizing a constant from 'the first env entry' would
    be wrong. Consumers needing full-length arrays (group keys, aggregate
    inputs, join keys) broadcast explicitly via broadcast_1d."""
    v = expr.value
    if v is None:
        def f(env):
            return jnp.zeros((), dtype=jnp.int64), jnp.ones((), dtype=bool)
        return f
    k = phys_kind(expr.ftype)
    if k == K_STR:
        raise DeviceUnsupported("bare string constants only valid in eq/in")
    if k == K_FLOAT:
        val = float(v)
        dt = jnp.float64
    else:
        val = int(v)
        dt = jnp.int64 if k != K_DATE else jnp.int32

    def f(env):
        return jnp.asarray(val, dtype=dt), jnp.zeros((), dtype=bool)
    return f


def broadcast_1d(d, nl, n):
    """Expand 0-d (constant) results to length n where a full array is
    structurally required."""
    if d.ndim == 0:
        d = jnp.broadcast_to(d, (n,))
    if nl.ndim == 0:
        nl = jnp.broadcast_to(nl, (n,))
    return d, nl


def _dec_scale(e):
    return e.ftype.scale if phys_kind(e.ftype) == K_DEC else 0


def _to_common_numeric(sf, cols):
    """Compile both args of a binary numeric op to a common kind.
    Returns (kind, fa, fb, scale)."""
    a, b = sf.args
    ka, kb = phys_kind(a.ftype), phys_kind(b.ftype)
    fa = compile_expr(a, cols)
    fb = compile_expr(b, cols)
    # string equality via dictionary codes
    if ka == K_STR or kb == K_STR:
        raise DeviceUnsupported("string args only supported in eq/in paths")
    if K_FLOAT in (ka, kb):
        def wrap(f, e):
            sc = _dec_scale(e)

            def g(env):
                d, n = f(env)
                d = d.astype(jnp.float64)
                if sc:
                    d = d / POW10[sc]
                return d, n
            return g
        return K_FLOAT, wrap(fa, a), wrap(fb, b), 0
    if K_DEC in (ka, kb):
        s = max(_dec_scale(a), _dec_scale(b))

        def wrap(f, e):
            sc = _dec_scale(e)

            def g(env):
                d, n = f(env)
                d = d.astype(jnp.int64)
                if s > sc:
                    d = d * POW10[s - sc]
                return d, n
            return g
        return K_DEC, wrap(fa, a), wrap(fb, b), s
    # ints / dates / datetimes
    promote_a = ka == K_DATE and b.ftype.tp in (TYPE_DATETIME, TYPE_TIMESTAMP)
    promote_b = kb == K_DATE and a.ftype.tp in (TYPE_DATETIME, TYPE_TIMESTAMP)

    def wrap(f, promote):
        def g(env):
            d, n = f(env)
            d = d.astype(jnp.int64)
            if promote:
                d = d * 86_400_000_000
            return d, n
        return g
    return K_INT, wrap(fa, promote_a), wrap(fb, promote_b), 0


_CMP_OPS = {"eq": lambda a, b: a == b, "ne": lambda a, b: a != b,
            "lt": lambda a, b: a < b, "le": lambda a, b: a <= b,
            "gt": lambda a, b: a > b, "ge": lambda a, b: a >= b}


def _compile_func(sf: ScalarFunc, cols):
    """Dispatch with a dictionary-pushdown fallback: a numeric function
    of one dict-encoded string column that the direct compiler declines
    (LENGTH, casts, string arithmetic coercions, …) host-evaluates over
    the dictionary into a LUT instead of falling back to the host path."""
    try:
        return _compile_func_direct(sf, cols)
    except DeviceUnsupported:
        f = _try_str_numeric_lut(sf, cols)
        if f is not None:
            return f
        raise


def _compile_func_direct(sf: ScalarFunc, cols):
    op = sf.op
    if op in _CMP_OPS:
        # string vs constant → dictionary code comparison (eq/ne only)
        a, b = sf.args
        if phys_kind(a.ftype) == K_STR or phys_kind(b.ftype) == K_STR:
            return _compile_str_cmp(sf, cols)
        kind, fa, fb, _s = _to_common_numeric(sf, cols)
        cmp = _CMP_OPS[op]

        def f(env):
            da, na = fa(env)
            db, nb = fb(env)
            return cmp(da, db).astype(jnp.int64), na | nb
        return f
    if op in ("add", "sub", "mul"):
        out_k = phys_kind(sf.ftype)
        if out_k == K_DEC and op == "mul":
            fa = _compile_scaled(sf.args[0], cols, _dec_scale(sf.args[0]))
            fb = _compile_scaled(sf.args[1], cols, _dec_scale(sf.args[1]))

            def f(env):
                da, na = fa(env)
                db, nb = fb(env)
                return da * db, na | nb
            return f
        if out_k == K_DEC:
            s = sf.ftype.scale
            fa = _compile_scaled(sf.args[0], cols, s)
            fb = _compile_scaled(sf.args[1], cols, s)
            fn = jnp.add if op == "add" else jnp.subtract

            def f(env):
                da, na = fa(env)
                db, nb = fb(env)
                return fn(da, db), na | nb
            return f
        kind, fa, fb, _s = _to_common_numeric(sf, cols)
        fn = {"add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply}[op]

        def f(env):
            da, na = fa(env)
            db, nb = fb(env)
            return fn(da, db), na | nb
        return f
    if op == "div":
        out_k = phys_kind(sf.ftype)
        if out_k == K_FLOAT:
            _k, fa, fb, _s = _to_common_numeric(sf, cols)

            def f(env):
                da, na = fa(env)
                db, nb = fb(env)
                zero = db == 0
                safe = jnp.where(zero, 1.0, db)
                return da / safe, na | nb | zero
            return f
        s1 = _dec_scale(sf.args[0])
        s2 = _dec_scale(sf.args[1])
        sr = sf.ftype.scale
        fa = _compile_scaled(sf.args[0], cols, s1)
        fb = _compile_scaled(sf.args[1], cols, s2)
        shift = POW10[sr + s2 - s1]

        def f(env):
            da, na = fa(env)
            db, nb = fb(env)
            zero = db == 0
            num = da * shift
            den = jnp.where(zero, 1, db)
            sign = jnp.where((num < 0) != (den < 0), -1, 1)
            q = (2 * jnp.abs(num) + jnp.abs(den)) // (2 * jnp.abs(den))
            return sign * q, na | nb | zero
        return f
    if op in ("and", "or"):
        fa = compile_expr(sf.args[0], cols)
        fb = compile_expr(sf.args[1], cols)
        if op == "and":
            def f(env):
                da, na = fa(env)
                db, nb = fb(env)
                ta = (da != 0) & ~na
                tb = (db != 0) & ~nb
                fa_ = (da == 0) & ~na
                fb_ = (db == 0) & ~nb
                res = ta & tb
                nulls = ~(fa_ | fb_) & (na | nb)
                return res.astype(jnp.int64), nulls
            return f

        def f(env):
            da, na = fa(env)
            db, nb = fb(env)
            ta = (da != 0) & ~na
            tb = (db != 0) & ~nb
            res = ta | tb
            nulls = ~res & (na | nb)
            return res.astype(jnp.int64), nulls
        return f
    if op == "not":
        fa = compile_expr(sf.args[0], cols)

        def f(env):
            d, n = fa(env)
            return (d == 0).astype(jnp.int64), n
        return f
    if op == "isnull":
        fa = compile_expr(sf.args[0], cols)

        def f(env):
            _d, n = fa(env)
            return n.astype(jnp.int64), jnp.zeros_like(n)
        return f
    if op == "neg":
        fa = compile_expr(sf.args[0], cols)

        def f(env):
            d, n = fa(env)
            return -d, n
        return f
    if op == "in_set":
        target = sf.args[0]
        values, has_null = sf.extra
        if phys_kind(target.ftype) == K_STR:
            return _compile_str_in(sf, cols)
        fa = compile_expr(target, cols)
        if len(values) == 0:
            # empty IN list (e.g. a HAVING-filtered subquery with no
            # qualifying rows): constant FALSE, NULL if the list's only
            # content was NULL — gathering from a 0-length array is a
            # trace error
            def f(env):
                d, n = fa(env)
                hit = jnp.zeros_like(d, dtype=jnp.int64)
                return hit, n | bool(has_null)
            return f
        sorted_vals = jnp.asarray(np.sort(np.asarray(values)))

        def f(env):
            d, n = fa(env)
            pos = jnp.searchsorted(sorted_vals, d)
            pos = jnp.clip(pos, 0, len(sorted_vals) - 1)
            hit = sorted_vals[pos] == d
            nulls = n | (~hit & bool(has_null))
            return hit.astype(jnp.int64), nulls
        return f
    if op == "case":
        return _compile_case(sf, cols)
    if op == "if":
        return _compile_case(ScalarFunc("case", sf.args, sf.ftype), cols)
    if op == "cast":
        return _compile_cast(sf, cols)
    if op == "coalesce":
        fs = [compile_expr(a, cols) for a in sf.args]
        tk = phys_kind(sf.ftype)
        if tk == K_STR:
            raise DeviceUnsupported("string coalesce")

        def f(env):
            out_d, out_n = fs[0](env)
            out_d = _coerce_kind(out_d, sf.args[0], sf.ftype)
            for fx, ax in zip(fs[1:], sf.args[1:]):
                d, n = fx(env)
                d = _coerce_kind(d, ax, sf.ftype)
                out_d = jnp.where(out_n, d, out_d)
                out_n = out_n & n
            return out_d, out_n
        return f
    if op in ("year", "month", "dayofmonth", "day"):
        arg = sf.args[0]
        fa = compile_expr(arg, cols)
        ak = phys_kind(arg.ftype)
        is_dt = arg.ftype.tp in (TYPE_DATETIME, TYPE_TIMESTAMP)
        if ak != K_DATE and not is_dt:
            raise DeviceUnsupported(f"{op}() on non-temporal for device")
        part = {"year": 0, "month": 1, "dayofmonth": 2, "day": 2}[op]

        def f(env):
            d, n = fa(env)
            days = (jnp.floor_divide(d.astype(jnp.int64), 86_400_000_000)
                    if is_dt else d.astype(jnp.int64))
            return _civil_from_days(days)[part], n
        return f
    if op == "abs":
        fa = compile_expr(sf.args[0], cols)

        def f(env):
            d, n = fa(env)
            return jnp.abs(d), n
        return f
    if op in ("like", "regexp"):
        return _compile_str_pattern(sf, cols)
    raise DeviceUnsupported(f"scalar op {op} not available on device")


# ---------------------------------------------------------------------------
# string-VALUED expressions: everything compiles to CODES into a sorted key
# dictionary (dictionary pushdown, generalized). A derived string expression
# — CASE over strings, SUBSTRING, UPPER, CONCAT with constants — either
# merges its arms' dictionaries (branches) or is evaluated host-side ONCE
# per distinct dictionary entry and becomes a device code-LUT. The per-
# distinct-value cost beats per-row for real data, and the device sees only
# int codes (reference: the coprocessor evaluates these per row over raw
# bytes — expression/builtin_string.go; per-distinct is the columnar win).
# ---------------------------------------------------------------------------

_IMPURE_OPS = frozenset({"rand", "uuid", "sleep"})


def compile_str_expr(expr, cols):
    """Compile a string-valued expression → (fn, key_dict, reps): fn(env)
    yields codes into the sorted `key_dict`; `reps` decodes codes back to
    output strings. Raises DeviceUnsupported outside the language."""
    if isinstance(expr, ExprColumn):
        dc = cols.get(expr.idx)
        if dc is None or dc.dictionary is None:
            raise DeviceUnsupported("no dictionary for string column")
        return compile_expr(expr, cols), dc.dictionary, dc.decode_dict()
    if isinstance(expr, Constant):
        if expr.value is None:
            e = np.array([b""], dtype=object)

            def f(env):
                return (jnp.zeros((), dtype=jnp.int64),
                        jnp.ones((), dtype=bool))
            return f, e, e
        v = (expr.value if isinstance(expr.value, bytes)
             else str(expr.value).encode())
        from ..utils.collate import is_ci, sort_key
        key = (sort_key(v, expr.ftype.collate)
               if is_ci(expr.ftype.collate) else v)

        def f(env):
            return (jnp.zeros((), dtype=jnp.int64),
                    jnp.zeros((), dtype=bool))
        return (f, np.array([key], dtype=object),
                np.array([v], dtype=object))
    if isinstance(expr, ScalarFunc) and expr.op in ("case", "if",
                                                    "coalesce"):
        return _compile_str_branch(expr, cols)
    if isinstance(expr, ScalarFunc):
        return _compile_str_dict_pushdown(expr, cols)
    raise DeviceUnsupported(
        f"{type(expr).__name__} string expression on device")


def _compile_str_branch(sf, cols):
    """String-valued CASE/IF/COALESCE: arms compile to their own code
    spaces, merged into one union dictionary via static remap tables."""
    from ..utils.collate import is_ci
    args = sf.args
    if is_ci(sf.ftype.collate) or any(
            is_ci(a.ftype.collate) for a in args
            if phys_kind(a.ftype) == K_STR):
        # arm key spaces would mix raw bytes with per-collation sort keys
        raise DeviceUnsupported("_ci string branches on device")
    if sf.op == "coalesce":
        conds = None
        arms = list(args)
    else:
        has_else = len(args) % 2 == 1
        pairs = (len(args) - (1 if has_else else 0)) // 2
        conds = [compile_expr(args[2 * p], cols) for p in range(pairs)]
        arms = [args[2 * p + 1] for p in range(pairs)]
        if has_else:
            arms.append(args[-1])
    compiled = [compile_str_expr(a, cols) for a in arms]
    all_keys = np.concatenate([kd for _f, kd, _r in compiled])
    all_reps = np.concatenate([r for _f, _kd, r in compiled])
    key_dict, first = np.unique(all_keys, return_index=True)
    reps = all_reps[first]
    remaps = [jnp.asarray(np.searchsorted(key_dict, kd).astype(np.int64))
              for _f, kd, _r in compiled]
    sizes = [len(kd) for _f, kd, _r in compiled]

    def arm(i, env):
        d, n = compiled[i][0](env)
        d = remaps[i][jnp.clip(d.astype(jnp.int64), 0, sizes[i] - 1)]
        return d, n

    if sf.op == "coalesce":
        def f(env):
            out_d, out_n = arm(0, env)
            for i in range(1, len(compiled)):
                d, n = arm(i, env)
                out_d = jnp.where(out_n, d, out_d)
                out_n = out_n & n
            return out_d, out_n
        return f, key_dict, reps

    n_conds = len(conds)

    def f(env):
        out = jnp.zeros((), dtype=jnp.int64)
        out_n = jnp.ones((), dtype=bool)
        decided = jnp.zeros((), dtype=bool)
        for p in range(n_conds):
            cd, cn = conds[p](env)
            cond = (cd != 0) & ~cn & ~decided
            rd, rn = arm(p, env)
            out = jnp.where(cond, rd, out)
            out_n = jnp.where(cond, rn, out_n)
            decided = decided | cond
        if len(arms) > n_conds:  # ELSE
            rd, rn = arm(len(arms) - 1, env)
            out = jnp.where(decided, out, rd)
            out_n = jnp.where(decided, out_n, rn)
        return out, out_n
    return f, key_dict, reps


def _single_str_col(expr, cols):
    """The one dict-encoded string column an expression reads, or raise."""
    used: set = set()
    expr.columns_used(used)
    if len(used) != 1:
        raise DeviceUnsupported(
            "dictionary pushdown needs exactly one column input")
    idx = next(iter(used))
    dc = cols.get(idx)
    if dc is None or dc.dictionary is None or phys_kind(dc.ftype) != K_STR:
        raise DeviceUnsupported("dictionary pushdown needs a string column")
    return idx, dc


def _host_eval_over_dict(expr, dc):
    """Evaluate `expr` host-side once per distinct dictionary entry PLUS
    one NULL input row → (values, nulls) of length len(dict)+1, where the
    last slot is the expression's output FOR NULL INPUT. Null-handling
    subexpressions (COALESCE/IFNULL/CASE) may map NULL to a value, so the
    LUT must carry the null slot instead of blindly propagating input
    nulls."""
    def check(e):
        if isinstance(e, ScalarFunc):
            if e.op in _IMPURE_OPS:
                raise DeviceUnsupported(f"impure {e.op} on device")
            for a in e.args:
                check(a)
    check(expr)
    from ..utils.chunk import Chunk as HChunk, Column as HColumn
    src = dc.decode_dict()
    n = len(src)
    data = np.empty(n + 1, dtype=object)
    data[:n] = np.asarray(src, dtype=object)
    data[n] = b""
    nulls = np.zeros(n + 1, dtype=bool)
    nulls[n] = True
    col = HColumn(dc.ftype, data, nulls)
    local = expr.transform_columns(lambda c: ExprColumn(0, c.ftype))
    return local.eval(HChunk([col]))


def _compile_str_dict_pushdown(sf, cols):
    """String→string function of one dict column: host-evaluate over the
    dictionary, build the output dictionary, device op = code LUT."""
    from ..utils.collate import is_ci
    if is_ci(sf.ftype.collate):
        raise DeviceUnsupported("_ci derived string on device")
    idx, dc = _single_str_col(sf, cols)
    data, nulls = _host_eval_over_dict(sf, dc)
    vals = np.array([v if isinstance(v, bytes) else str(v).encode()
                     for v in data], dtype=object)
    key_dict, inv = np.unique(vals, return_inverse=True)
    code_map = jnp.asarray(inv.astype(np.int64))
    null_lut = jnp.asarray(np.asarray(nulls, dtype=bool))
    nd = len(dc.dictionary)

    def f(env):
        d, n = env[idx]
        # NULL input rows read the null slot (index nd) — the expression
        # may map NULL to a value (COALESCE etc.)
        c = jnp.where(n, nd, jnp.clip(d.astype(jnp.int64), 0, nd - 1))
        return code_map[c], null_lut[c]
    return f, key_dict, key_dict


def _try_str_numeric_lut(sf, cols):
    """Numeric-valued function of one dict string column (LENGTH, casts,
    string→number …): host-evaluate over the dictionary → numeric LUT.
    Returns None when the shape doesn't apply."""
    k = phys_kind(sf.ftype)
    if k == K_STR:
        return None
    try:
        idx, dc = _single_str_col(sf, cols)
    except DeviceUnsupported:
        return None
    data, nulls = _host_eval_over_dict(sf, dc)
    if k == K_FLOAT:
        arr = np.asarray(data, dtype=np.float64)
    else:
        arr = np.asarray(data).astype(np.int64)
    lut = jnp.asarray(arr)
    null_lut = jnp.asarray(np.asarray(nulls, dtype=bool))
    nd = len(dc.dictionary)

    def f(env):
        d, n = env[idx]
        c = jnp.where(n, nd, jnp.clip(d.astype(jnp.int64), 0, nd - 1))
        return lut[c], null_lut[c]
    return f


def _compile_str_pattern(sf, cols):
    """LIKE / REGEXP on a dict-encoded string column against a constant
    pattern: evaluate the predicate HOST-SIDE over the (small, distinct)
    dictionary once, then the device op is a boolean table lookup by code
    — dictionary pushdown (the reference evaluates LIKE per row over raw
    bytes, expression/builtin_like.go; per distinct value beats per row)."""
    from ..expression.core import like_to_regex
    import re as _re
    target, pat = sf.args[0], sf.args[1]
    if phys_kind(target.ftype) != K_STR:
        raise DeviceUnsupported(f"{sf.op} target must be a string value")
    if not isinstance(pat, Constant):
        raise DeviceUnsupported(f"{sf.op} pattern must be a constant")
    ft, key_dict, _reps = compile_str_expr(target, cols)
    if pat.value is None:
        def f(env):
            return jnp.zeros((), dtype=jnp.int64), jnp.ones((), dtype=bool)
        return f
    from ..utils.collate import is_ci
    ci = is_ci(target.ftype.collate)
    pv = (pat.value if isinstance(pat.value, bytes)
          else str(pat.value).encode())
    if sf.op == "like":
        if ci:
            # _ci dictionary holds sort keys: match the sort-keyed pattern
            # (same as the host ci path, which also uses the default
            # escape — core.py _eval_like)
            rx = like_to_regex(_expr_const_key(target, pv))
        else:
            # sf.extra carries the escape-aware regex the builder compiled
            # (LIKE ... ESCAPE '!'); rebuilding here would drop the escape
            rx = sf.extra if sf.extra is not None else like_to_regex(pv)
        match = rx.match
    else:
        if ci:
            raise DeviceUnsupported("regexp on _ci column")
        rx = _re.compile(pv)
        match = rx.search
    nd = len(key_dict)
    bits = np.zeros(nd, dtype=bool)
    for i, v in enumerate(key_dict):
        b = v if isinstance(v, bytes) else str(v).encode()
        bits[i] = match(b) is not None
    lut = jnp.asarray(bits)

    def f(env):
        d, n = ft(env)
        hit = lut[jnp.clip(d.astype(jnp.int64), 0, nd - 1)]
        return hit.astype(jnp.int64), n
    return f


def _civil_from_days(z):
    """days-since-epoch → (y, m, d). Howard Hinnant's civil_from_days,
    branch-free — pure integer ops, MXU-adjacent friendly."""
    z = z + 719468
    era = jnp.where(z >= 0, z, z - 146096) // 146097
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = jnp.where(mp < 10, mp + 3, mp - 9)
    y = jnp.where(m <= 2, y + 1, y)
    return y, m, d


def _compile_scaled(e, cols, target_scale):
    f = compile_expr(e, cols)
    sc = _dec_scale(e)
    k = phys_kind(e.ftype)
    if k in (K_FLOAT, K_STR):
        raise DeviceUnsupported("float→decimal on device")

    def g(env):
        d, n = f(env)
        d = d.astype(jnp.int64)
        if target_scale > sc:
            d = d * POW10[target_scale - sc]
        return d, n
    return g


def _coerce_kind(d, e, out_ft):
    k, ok = phys_kind(e.ftype), phys_kind(out_ft)
    if ok == K_FLOAT:
        d = d.astype(jnp.float64)
        if k == K_DEC:
            d = d / POW10[e.ftype.scale]
        return d
    if ok == K_DEC:
        d = d.astype(jnp.int64)
        sc = _dec_scale(e)
        if out_ft.scale > sc:
            d = d * POW10[out_ft.scale - sc]
        return d
    return d.astype(jnp.int64)


def _compile_case(sf, cols):
    args = sf.args
    has_else = len(args) % 2 == 1
    pairs = (len(args) - (1 if has_else else 0)) // 2
    if phys_kind(sf.ftype) == K_STR:
        raise DeviceUnsupported("string CASE on device")
    fs = [compile_expr(a, cols) for a in args]

    def f(env):
        # scalar seeds broadcast up against whichever condition/result
        # array they meet (constants are 0-d — see _compile_const)
        dt = jnp.float64 if phys_kind(sf.ftype) == K_FLOAT else jnp.int64
        out = jnp.zeros((), dtype=dt)
        out_n = jnp.ones((), dtype=bool)
        decided = jnp.zeros((), dtype=bool)
        for p in range(pairs):
            cd, cn = fs[2 * p](env)
            cond = (cd != 0) & ~cn & ~decided
            rd, rn = fs[2 * p + 1](env)
            rd = _coerce_kind(rd, args[2 * p + 1], sf.ftype)
            out = jnp.where(cond, rd, out)
            out_n = jnp.where(cond, rn, out_n)
            decided = decided | cond
        if has_else:
            rd, rn = fs[-1](env)
            rd = _coerce_kind(rd, args[-1], sf.ftype)
            out = jnp.where(decided, out, rd)
            out_n = jnp.where(decided, out_n, rn)
        return out, out_n
    return f


def _compile_cast(sf, cols):
    src = sf.args[0]
    f = compile_expr(src, cols)
    sk, tk = phys_kind(src.ftype), phys_kind(sf.ftype)
    if K_STR in (sk, tk):
        raise DeviceUnsupported("string casts on device")

    def g(env):
        d, n = f(env)
        if tk == K_FLOAT:
            d = d.astype(jnp.float64)
            if sk == K_DEC:
                d = d / POW10[src.ftype.scale]
            return d, n
        if tk == K_DEC:
            if sk == K_DEC:
                diff = sf.ftype.scale - src.ftype.scale
                if diff >= 0:
                    return d.astype(jnp.int64) * POW10[diff], n
                den = POW10[-diff]
                sign = jnp.where(d < 0, -1, 1)
                q = (2 * jnp.abs(d) + den) // (2 * den)
                return sign * q, n
            if sk == K_FLOAT:
                return jnp.round(d * POW10[sf.ftype.scale]).astype(jnp.int64), n
            return d.astype(jnp.int64) * POW10[sf.ftype.scale], n
        # int target
        if sk == K_DEC:
            den = POW10[src.ftype.scale]
            sign = jnp.where(d < 0, -1, 1)
            q = (2 * jnp.abs(d) + den) // (2 * den)
            return sign * q, n
        if sk == K_FLOAT:
            return jnp.round(d).astype(jnp.int64), n
        return d.astype(jnp.int64), n
    return g


def _compile_str_cmp(sf, cols):
    a, b = sf.args
    # ordering comparisons on dictionary codes are valid because every key
    # dictionary is sorted (np.unique bytes / sort-key classes for _ci)
    if isinstance(b, Constant) and not isinstance(a, Constant):
        lhs, const = a, b
    elif isinstance(a, Constant) and not isinstance(b, Constant):
        lhs, const = b, a
        # flip comparison direction
        sf = ScalarFunc({"lt": "gt", "gt": "lt", "le": "ge", "ge": "le"}.get(
            sf.op, sf.op), [b, a], sf.ftype)
    else:
        return _compile_str_cmp_exprs(sf, cols)
    fl, key_dict, _reps = compile_str_expr(lhs, cols)
    if const.value is None:
        def f(env):
            return (jnp.zeros((), dtype=jnp.int64),
                    jnp.ones((), dtype=bool))
        return f
    v = _expr_const_key(lhs, const.value)
    code = _key_code_for(key_dict, v)
    exact = code >= 0
    pos = code if exact else int(np.searchsorted(key_dict, v))
    if not exact:
        code = pos - 0.5  # between codes for range compares
    op = sf.op
    cmp = _CMP_OPS[op]

    def f(env):
        d, n = fl(env)
        res = cmp(d.astype(jnp.float64), code) if not exact else cmp(d, pos)
        return res.astype(jnp.int64), n
    return f


def _expr_const_key(expr, const_val):
    """A bytes constant in a string EXPRESSION's key space (its collation
    decides whether the key is the raw bytes or the sort key)."""
    from ..utils.collate import is_ci, sort_key
    v = const_val if isinstance(const_val, bytes) else str(const_val).encode()
    if is_ci(expr.ftype.collate):
        v = sort_key(v, expr.ftype.collate)
    return v


def _key_code_for(key_dict, key):
    """Exact code of `key` in a sorted key dictionary, or -2 (never
    matches: codes are >= 0)."""
    pos = int(np.searchsorted(key_dict, key))
    if pos < len(key_dict) and key_dict[pos] == key:
        return pos
    return -2


def _compile_str_cmp_exprs(sf, cols):
    """expr-vs-expr string comparison (col=col included): both sides map
    into the UNION of their key dictionaries, where code order is value
    order for both — then it's an int compare."""
    from ..utils.collate import is_ci
    a, b = sf.args
    ca, cb = a.ftype.collate, b.ftype.collate
    if (is_ci(ca) or is_ci(cb)) and ca != cb:
        # different sort-key spaces cannot union consistently
        raise DeviceUnsupported("mixed-collation string compare on device")
    fa, kda, _ra = compile_str_expr(a, cols)
    fb, kdb, _rb = compile_str_expr(b, cols)
    union = np.unique(np.concatenate([kda, kdb]))
    mapa = jnp.asarray(np.searchsorted(union, kda).astype(np.int64))
    mapb = jnp.asarray(np.searchsorted(union, kdb).astype(np.int64))
    na, nb = len(kda), len(kdb)
    cmp = _CMP_OPS[sf.op]

    def f(env):
        da, nla = fa(env)
        db, nlb = fb(env)
        ua = mapa[jnp.clip(da.astype(jnp.int64), 0, na - 1)]
        ub = mapb[jnp.clip(db.astype(jnp.int64), 0, nb - 1)]
        return cmp(ua, ub).astype(jnp.int64), nla | nlb
    return f


def _compile_str_in(sf, cols):
    target = sf.args[0]
    values, has_null = sf.extra
    ft, key_dict, _reps = compile_str_expr(target, cols)

    codes = sorted(set(
        c for c in (_key_code_for(key_dict, _expr_const_key(target, v))
                    for v in values) if c >= 0))
    code_arr = jnp.asarray(np.asarray(codes, dtype=np.int64)) if codes else None

    def f(env):
        d, n = ft(env)
        if code_arr is None:
            hit = jnp.zeros(d.shape[0], dtype=bool)
        else:
            pos = jnp.clip(jnp.searchsorted(code_arr, d), 0, len(codes) - 1)
            hit = code_arr[pos] == d
        nulls = n | (~hit & bool(has_null))
        return hit.astype(jnp.int64), nulls
    return f


# ---------------------------------------------------------------------------
# fused aggregation pipeline
# ---------------------------------------------------------------------------

def _seg_running(comb_val, is_new, z):
    """Segmented running reduction: resets at every True in is_new. Classic
    (flag, value) associative-scan operator — log-depth, fully vectorized,
    no scatter (scatters serialize on TPU)."""
    def comb(a, b):
        fa, va = a
        fb, vb = b
        return fa | fb, jnp.where(fb, vb, comb_val(va, vb))
    _f, run = jax.lax.associative_scan(comb, (is_new, z))
    return run


def _group_spans(is_new, kept, n, capacity):
    """Group boundary arithmetic shared by the single-chip kernel and the
    MPP partial/final stages: starts from a top-k selection, end_g = next
    start (or kept for the last group). Returns (starts, ends, end_idx,
    span_sum) where span_sum(z) = per-group sums of z via exclusive prefix
    sums (exact for ints — two's-complement differences cancel; float sums
    must use _seg_running instead to keep rounding error group-local).

    Boundary positions come from a searchsorted over the running group id
    (cumsum of is_new), NOT jnp.nonzero(size=...) nor top_k: nonzero
    lowers to a serialized path on TPU (~500ms at 6M rows), and top_k is a
    partial sort (measured 188ms at 600k/262k-capacity on the CPU backend
    vs 43ms for the two binary searches). gid is non-decreasing by
    construction, so `starts[g] = first row with gid ≥ g` is exact, and
    rows past the last group (g ≥ n_groups) return n — the same fill
    nonzero's fill_value produced."""
    gid = jnp.cumsum(is_new) - 1
    starts = jnp.searchsorted(gid, jnp.arange(capacity), side="left"
                              ).astype(jnp.int64)
    ends = jnp.minimum(jnp.concatenate(
        [starts[1:], jnp.full(1, n, dtype=starts.dtype)]), kept)
    end_idx = jnp.clip(ends - 1, 0, jnp.maximum(n - 1, 0))

    def span_sum(z):
        c = jnp.concatenate([jnp.zeros(1, dtype=z.dtype), jnp.cumsum(z)])
        return c[ends] - c[jnp.minimum(starts, n)]

    return starts, ends, end_idx, span_sum


#: dense-bucket aggregation bound: bucket arrays up to 2^26 slots (the
#: packed-key space) are cheaper than one 100k+-element sort on the XLA CPU
#: backend, where sort lowers to a slow single-threaded path. Bucket
#: memory scales with the ACTUAL key span, capped by the BYTE budget
#: below (26 bits + one value column ≈ 4.3GB transient — the budget, not
#: this constant, is usually the binding bound). A 60M-value l_orderkey
#: GROUP BY (TPC-H Q18's inner agg at SF10, 26-bit span) stays on O(n)
#: scatters instead of falling onto the serial sort (measured: the sort
#: path made SF10 Q18 7x slower than host; the path only exists on the
#: CPU backend, so the budget sizes against host RAM, not HBM)
_SCATTER_AGG_BITS = 26


def _host_ram_bytes() -> int:
    try:
        return os.sysconf("SC_PAGE_SIZE") * os.sysconf("SC_PHYS_PAGES")
    except (ValueError, OSError, AttributeError):
        return 8 << 30


#: peak bytes the scatter path may hold in bucket arrays at once —
#: a quarter of physical RAM, capped at 6GB: the buckets live inside XLA
#: where the engine's quota tracker can't see them, so the bound must
#: come from the machine, not a constant (a 26-bit span with one value
#: column pins ~4.3GB transient; on a small host that must divert to
#: the sort path instead of inviting the OOM killer)
_SCATTER_AGG_BUDGET_BYTES = min(6 << 30, max(_host_ram_bytes() // 4, 1 << 30))


def _agg_scatter_impl(key_cols, key_nulls, val_cols, val_nulls, mask,
                      n_keys, agg_ops, capacity, pack):
    """Dense-bucket aggregation: bucket id = the statically packed group
    key; per aggregate ONE scatter-add/min/max over the bucket space, then
    a compaction scatter into the capacity-sized output slots.

    XLA-CPU-only lowering choice (see _agg_impl): scatters there are tight
    O(n) loops (~100x faster than the backend's sort), while on TPU
    non-unique scatters serialize and the sort path wins. Both produce
    identical group sets; bucket order = packed-key order, and the
    representative row per group is the scatter-min of kept row positions,
    so first_row/key decode semantics match the stable-sort path."""
    n = mask.shape[0]
    total_bits = sum(b for b, _o in pack)
    B = 1 << total_bits
    bucket = jnp.zeros(n, dtype=jnp.int64)
    for i, (bits, offset) in enumerate(pack):
        shifted = (key_cols[i].astype(jnp.int64)
                   + jnp.asarray(offset + 1, dtype=jnp.int64))
        v = jnp.where(key_nulls[i], jnp.zeros((), dtype=jnp.int64), shifted)
        bucket = (bucket << bits) | v
    bucket = jnp.clip(bucket, 0, B - 1)
    pos = jnp.arange(n)
    ones = jnp.where(mask, 1, 0)
    cnt_rows = jnp.zeros(B, dtype=jnp.int64).at[bucket].add(ones)
    rep = jnp.full(B, n, dtype=jnp.int64).at[bucket].min(
        jnp.where(mask, pos, n))
    live = cnt_rows > 0
    n_groups = jnp.sum(live)
    rank = jnp.cumsum(live) - 1
    tgt = jnp.where(live, rank, capacity)  # dead buckets drop on compact

    def compact(arr_B):
        out_dt = arr_B.dtype
        return jnp.zeros(capacity, dtype=out_dt).at[tgt].set(
            arr_B, mode="drop")

    rep_safe = jnp.clip(rep, 0, jnp.maximum(n - 1, 0))
    key_out = tuple(compact(k[rep_safe]) for k in key_cols)
    key_null_out = tuple(compact(kn[rep_safe]) for kn in key_nulls)

    nn_cache = {}

    def nonnull_counts(j):
        hit = nn_cache.get(id(val_nulls[j]))
        if hit is None:
            keep = mask & ~val_nulls[j]
            hit = jnp.zeros(B, dtype=jnp.int64).at[bucket].add(
                jnp.where(keep, 1, 0))
            nn_cache[id(val_nulls[j])] = hit
        return hit

    results = []
    result_nulls = []
    for j, opn in enumerate(agg_ops):
        v = val_cols[j]
        vn = val_nulls[j]
        keep = mask & ~vn
        if opn == "first":
            results.append(compact(v[rep_safe]))
            result_nulls.append(compact(vn[rep_safe]))
            continue
        nn = nonnull_counts(j)
        if opn == "count":
            results.append(compact(nn))
            result_nulls.append(jnp.zeros(capacity, dtype=bool))
            continue
        if opn == "sum_i":
            acc = jnp.zeros(B, dtype=jnp.int64).at[bucket].add(
                jnp.where(keep, v.astype(jnp.int64), 0))
        elif opn == "sum_f":
            acc = jnp.zeros(B, dtype=jnp.float64).at[bucket].add(
                jnp.where(keep, v.astype(jnp.float64), 0.0))
        elif opn == "min":
            big = (jnp.inf if jnp.issubdtype(v.dtype, jnp.floating)
                   else jnp.iinfo(v.dtype).max)
            acc = jnp.full(B, big, dtype=v.dtype).at[bucket].min(
                jnp.where(keep, v, big))
        elif opn == "max":
            small = (-jnp.inf if jnp.issubdtype(v.dtype, jnp.floating)
                     else jnp.iinfo(v.dtype).min)
            acc = jnp.full(B, small, dtype=v.dtype).at[bucket].max(
                jnp.where(keep, v, small))
        else:
            raise ValueError(opn)
        results.append(compact(acc))
        result_nulls.append(compact(nn) == 0)
    valid = jnp.arange(capacity) < n_groups
    return (key_out, key_null_out, tuple(results), tuple(result_nulls),
            n_groups, valid)


def _agg_impl(key_cols, key_nulls, val_cols, val_nulls, mask,
              n_keys, agg_ops, capacity, pack=None):
    """One fused kernel: filter mask + group-by + aggregate.

    Sort-based grouping + boundary arithmetic — the XLA/TPU-native answer to
    the reference's hash tables (executor/aggregate.go): static shapes, no
    data-dependent control flow, and NO scatters (XLA lowers scatter-adds to
    a serialized loop on TPU; sort + cumsum + gather are all parallel).
    Per aggregate: exclusive-prefix-sum, then sum over a group = csum[end] -
    csum[start]; min/max via segmented associative scan. Groups beyond
    `capacity` are detected (n_groups > capacity) and the caller retries
    with a bigger static capacity — one extra compile, never wrong results.

    key_cols: tuple of int64 arrays (dict codes / ints). agg_ops: tuple of
    ("sum_i"|"sum_f"|"count"|"min"|"max"|"first") aligned with val_cols.

    pack: optional static tuple of (bits, offset) per key when every key's
    value range fits a known bit width (dict codes, dates). All keys, their
    null flags, and the filter mask then fold into ONE sort key — int32
    when it fits (64-bit ALU ops are emulated pairs on TPU) — giving one
    argsort instead of 2·n_keys+1. NULL packs as 0 (its own group);
    filtered-out rows pack as the dtype max and sort last.
    """
    if (pack is not None
            and sum(b for b, _o in pack) <= _SCATTER_AGG_BITS
            # live bucket arrays scale with the aggregate count: cnt +
            # rep + rank + tgt + live + per-agg acc + nullable nn caches
            # all stay resident through compaction — bound total BYTES,
            # not just key bits, or a many-column agg at 25 bits pins
            # gigabytes of 32M-slot arrays at once
            and (1 << sum(b for b, _o in pack)) * (2 * len(val_cols) + 6)
            * 8 <= _SCATTER_AGG_BUDGET_BYTES
            and "cnt_dist" not in agg_ops
            and jax.default_backend() == "cpu"):
        # backend-adaptive lowering: dense-bucket scatters beat the XLA CPU
        # backend's (slow, serial) sort by ~100x; on TPU scatters serialize
        # and the sort+segment path below is the right shape
        return _agg_scatter_impl(key_cols, key_nulls, val_cols, val_nulls,
                                 mask, n_keys, agg_ops, capacity, pack)
    n = mask.shape[0]
    kept = jnp.sum(mask)
    pos = jnp.arange(n)
    in_range = pos < kept
    if pack is not None:
        total_bits = sum(b for b, _o in pack)
        dt = jnp.int32 if total_bits < 31 else jnp.int64
        packed = jnp.zeros(n, dtype=dt)
        for i, (bits, offset) in enumerate(pack):
            # add the offset BEFORE narrowing: a large-valued key with a
            # small span (decimals, sparse ids) overflows int32 if cast
            # first; the shifted value always fits `bits`
            shifted = (key_cols[i].astype(jnp.int64)
                       + jnp.asarray(offset + 1, dtype=jnp.int64)).astype(dt)
            v = jnp.where(key_nulls[i], jnp.zeros((), dtype=dt), shifted)
            packed = (packed << bits) | v
        sort_val = jnp.where(mask, packed, jnp.iinfo(dt).max)
        order = jnp.argsort(sort_val, stable=True)
        sv = sort_val[order]
        prev = jnp.concatenate([sv[:1], sv[:-1]])
        is_new = (jnp.zeros(n, dtype=bool).at[0].set(n > 0) | (sv != prev))
        is_new = is_new & in_range
    else:
        # combined sort: minor-to-major stable argsort over keys, then
        # kept-first. Each key is the compound (null_flag, masked value) —
        # null is its own most-significant bit so a NULL never collides
        # with any real value (NULL ≠ -1; GROUP BY groups NULLs apart from
        # values). The value is NULL-MASKED to 0: NULL rows carry
        # arbitrary raw data (join-gather garbage), and sorting by it
        # would interleave rows of distinct groups that differ only in
        # minor keys, splintering the group blocks.
        order = jnp.arange(n)
        for i in range(n_keys - 1, -1, -1):
            mk = jnp.where(key_nulls[i], 0, key_cols[i])
            order = order[jnp.argsort(mk[order], stable=True)]
            order = order[jnp.argsort(key_nulls[i][order], stable=True)]
        order = order[jnp.argsort(~mask[order], stable=True)]
        # boundary flags on the sorted, kept prefix
        is_new = jnp.zeros(n, dtype=bool).at[0].set(n > 0)
        for i in range(n_keys):
            k = key_cols[i][order]
            kn = key_nulls[i][order]
            prev = jnp.concatenate([k[:1], k[:-1]])
            prev_n = jnp.concatenate([kn[:1], kn[:-1]])
            changed = jnp.where(kn | prev_n, kn != prev_n, k != prev)
            is_new = is_new | changed
        is_new = is_new & in_range
    n_groups = jnp.sum(is_new)
    # slots past n_groups hold garbage — callers slice [:n_groups] / mask
    # with `valid`
    starts, ends, end_idx, span_sum = _group_spans(is_new, kept, n, capacity)
    # representative row (first of group in sort order = first in original
    # order for equal keys, since the sorts are stable)
    rep_safe = jnp.clip(order[jnp.clip(starts, 0, jnp.maximum(n - 1, 0))],
                        0, jnp.maximum(n - 1, 0))
    key_out = tuple(k[rep_safe] for k in key_cols)
    key_null_out = tuple(kn[rep_safe] for kn in key_nulls)
    # -- batched count/sum_i path: ALL integer sums and their non-null
    # counters fold into ONE (m, n) matrix — one axis-1 gather by `order`,
    # one 2D cumsum, one boundary subtraction. Per-slot gathers+cumsums
    # were the kernel's dominant cost (~135ms/slot at 6M rows vs ~30ms
    # batched; measured on v5e over the serving fabric).
    batch_rows = []          # rows of the (m, n) matrix, pre-sort order
    slot_plan = {}           # j -> ("count", nn_row) | ("sum_i", nn_row, v_row)
    nn_rows_by_src = {}      # id(val_nulls[j]) -> row (avg = sum+count over
    #                          the same column: share one indicator row)
    for j, opn in enumerate(agg_ops):
        if opn not in ("count", "sum_i"):
            continue
        nn_row = nn_rows_by_src.get(id(val_nulls[j]))
        if nn_row is None:
            nn_row = len(batch_rows)
            batch_rows.append((~(val_nulls[j] | ~mask)).astype(jnp.int64))
            nn_rows_by_src[id(val_nulls[j])] = nn_row
        if opn == "count":
            slot_plan[j] = ("count", nn_row)
        else:
            v64 = val_cols[j].astype(jnp.int64)
            v_row = len(batch_rows)
            batch_rows.append(jnp.where(val_nulls[j] | ~mask, 0, v64))
            slot_plan[j] = ("sum_i", nn_row, v_row)
    spans2d = None
    if batch_rows:
        M = jnp.stack(batch_rows, axis=0)          # (m, n)
        SM = jnp.take(M, order, axis=1)            # one gather
        C = jnp.concatenate(
            [jnp.zeros((M.shape[0], 1), dtype=jnp.int64),
             jnp.cumsum(SM, axis=1)], axis=1)
        spans2d = C[:, ends] - C[:, jnp.minimum(starts, n)]

    results = []
    result_nulls = []
    for j, opn in enumerate(agg_ops):
        if opn == "first":
            # first row's own value AND null flag (mirrors host first_row;
            # a NULL in the representative row must stay NULL)
            results.append(val_cols[j][rep_safe])
            result_nulls.append(val_nulls[j][rep_safe])
            continue
        if opn == "cnt_dist":
            # COUNT(DISTINCT v): re-sort with the value as the MINOR key
            # — the group blocks land on the SAME positional spans (equal
            # multiset of group keys, stable order), so the order-1 span
            # machinery applies unchanged; distinct = run starts among
            # kept non-null rows (NULLs sort last per group and never
            # start a run). Reference: executor/aggfuncs count distinct
            # via a per-group hash set; sorted runs are the static-shape
            # equivalent.
            v64 = val_cols[j].astype(jnp.int64)
            if pack is not None:
                order2 = jnp.lexsort((v64, val_nulls[j], sort_val))
            else:
                order2 = jnp.arange(n)
                order2 = order2[jnp.argsort(v64[order2], stable=True)]
                order2 = order2[jnp.argsort(val_nulls[j][order2],
                                            stable=True)]
                for i in range(n_keys - 1, -1, -1):
                    # NULL-MASKED key: a NULL group's rows carry garbage
                    # raw key values; sorting by them would cluster the
                    # group internally and restart value runs at every
                    # cluster boundary (overcounting distinct). Masking
                    # to 0 keeps the whole null group one value-sorted
                    # block; the null-flag stage still separates it from
                    # a real 0-keyed group.
                    mk = jnp.where(key_nulls[i], 0, key_cols[i])
                    order2 = order2[jnp.argsort(mk[order2], stable=True)]
                    order2 = order2[jnp.argsort(key_nulls[i][order2],
                                                stable=True)]
                order2 = order2[jnp.argsort(~mask[order2], stable=True)]
            v2 = v64[order2]
            vn2 = val_nulls[j][order2]
            prev_v2 = jnp.concatenate([v2[:1], v2[:-1]])
            new_run = is_new | (v2 != prev_v2)
            live = ~vn2 & in_range & mask[order2]
            results.append(span_sum(jnp.where(live & new_run, 1, 0)
                                    .astype(jnp.int64)))
            result_nulls.append(jnp.zeros(capacity, dtype=bool))
            continue
        if opn == "count":
            _tag, nn_row = slot_plan[j]
            results.append(spans2d[nn_row])
            result_nulls.append(jnp.zeros(capacity, dtype=bool))
            continue
        if opn == "sum_i":
            _tag, nn_row, v_row = slot_plan[j]
            results.append(spans2d[v_row])
            result_nulls.append(spans2d[nn_row] == 0)
            continue
        v = val_cols[j][order]
        vn = val_nulls[j][order] | ~in_range
        nonnull = span_sum((~vn).astype(jnp.int64))
        if opn == "sum_f":
            # segmented scan, NOT prefix-sum differences: c[end]-c[start]
            # carries the whole column's magnitude into each group's
            # rounding error (catastrophic cancellation); the scan resets
            # per group so error stays group-local
            run = _seg_running(jnp.add, is_new,
                               jnp.where(vn, 0.0, v.astype(jnp.float64)))
            results.append(run[end_idx])
        elif opn == "min":
            big = (jnp.inf if jnp.issubdtype(v.dtype, jnp.floating)
                   else jnp.iinfo(v.dtype).max)
            run = _seg_running(jnp.minimum, is_new, jnp.where(vn, big, v))
            results.append(run[end_idx])
        elif opn == "max":
            small = (-jnp.inf if jnp.issubdtype(v.dtype, jnp.floating)
                     else jnp.iinfo(v.dtype).min)
            run = _seg_running(jnp.maximum, is_new, jnp.where(vn, small, v))
            results.append(run[end_idx])
        else:
            raise ValueError(opn)
        result_nulls.append(nonnull == 0)
    valid = jnp.arange(capacity) < n_groups
    return key_out, key_null_out, tuple(results), tuple(result_nulls), n_groups, valid


#: compile observability hooks, installed by executor.device_exec at
#: import so the standalone kernels below (join match, topk, graft agg
#: entry) meter retraces and compile seconds into the same pipe-cache
#: stats as the fused pipelines. All None → unobserved plain jit.
_trace_cb = None        # () -> None, called once per retrace
_tls_traces = None      # () -> this thread's trace count
_charge_compile = None  # seconds -> None


def _note_trace():
    if _trace_cb is not None:
        _trace_cb()


def observed_jit(fn, **jit_kw):
    """jax.jit + compile accounting (mirror of device_exec._timed_jit for
    kernels living below the executor layer): the body must call
    _note_trace(); a dispatch whose trace count moved charges its wall
    time as compile seconds."""
    import time as _time
    jfn = jax.jit(fn, **jit_kw)

    def run(*args, **kw):
        if _tls_traces is None:
            return jfn(*args, **kw)
        before = _tls_traces()
        t0 = _time.perf_counter()
        out = jfn(*args, **kw)
        if _tls_traces() > before and _charge_compile is not None:
            _charge_compile(_time.perf_counter() - t0)
        return out
    return run


def _agg_entry(key_cols, key_nulls, val_cols, val_nulls, mask,
               n_keys, agg_ops, capacity, pack=None):
    # thin wrapper: _agg_impl itself also traces INSIDE fused pipelines,
    # which count their own traces — only the standalone entry notes here
    _note_trace()
    return _agg_impl(key_cols, key_nulls, val_cols, val_nulls, mask,
                     n_keys=n_keys, agg_ops=agg_ops, capacity=capacity,
                     pack=pack)


#: jitted standalone entry (graft entry / direct kernel callers); the SQL
#: executor instead traces _agg_impl inside its own fused pipeline jit
_agg_kernel = observed_jit(
    _agg_entry, static_argnames=("n_keys", "agg_ops", "capacity", "pack"))

# ---------------------------------------------------------------------------
# two-pass sort join kernels
# ---------------------------------------------------------------------------

def _join_count_impl(build_key, probe_key, build_null, probe_null):
    """Pass 1: sort build side, count matches per probe row."""
    _note_trace()
    order = jnp.argsort(build_key, stable=True)
    sb = build_key[order]
    lo = jnp.searchsorted(sb, probe_key, side="left")
    hi = jnp.searchsorted(sb, probe_key, side="right")
    cnt = jnp.where(probe_null, 0, hi - lo)
    return order, sb, lo, cnt


_join_count_kernel = observed_jit(_join_count_impl)


def _join_expand_impl(order, lo, cnt, build_null, total):
    """Pass 2 (static total): expand match pairs."""
    _note_trace()
    cum = jnp.cumsum(cnt)
    pos = jnp.arange(total, dtype=jnp.int64)
    probe_idx = jnp.searchsorted(cum, pos, side="right")
    base = jnp.where(probe_idx > 0, cum[jnp.clip(probe_idx - 1, 0, None)], 0)
    within = pos - base
    safe_probe = jnp.clip(probe_idx, 0, lo.shape[0] - 1)
    bpos = lo[safe_probe] + within
    build_idx = order[jnp.clip(bpos, 0, order.shape[0] - 1)]
    keep = ~build_null[build_idx]
    return probe_idx, build_idx, keep


_join_expand_kernel = observed_jit(_join_expand_impl,
                                   static_argnames=("total",))


def device_join_match(build_keys, probe_keys):
    """Mirror of ops.host.join_match with device kernels. build_keys /
    probe_keys: [(np data int64, np nulls)] — pre-combined single key column
    (caller combines multi-column keys via host factorization for now).
    Returns numpy (probe_idx, build_idx)."""
    bk, bn = build_keys
    pk, pn = probe_keys
    order, _sb, lo, cnt = _join_count_kernel(
        jnp.asarray(bk), jnp.asarray(pk), jnp.asarray(bn), jnp.asarray(pn))
    total = int(jnp.sum(cnt))
    if total == 0:
        return np.zeros(0, dtype=np.int64), np.zeros(0, dtype=np.int64)
    probe_idx, build_idx, keep = _join_expand_kernel(
        order, lo, cnt, jnp.asarray(bn), total)
    keep = np.asarray(keep)
    return np.asarray(probe_idx)[keep], np.asarray(build_idx)[keep]
