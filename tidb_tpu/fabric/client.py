"""Minimal MySQL text-protocol client for the fleet: bench_serve's
multi-process mode and the fabric tests drive worker processes over the
real wire with it (no external mysql lib in the image).

Deliberately small: handshake (native password), COM_QUERY with text
resultsets, COM_QUIT.  The handshake's connection id is exposed — under
the fabric its high bits carry the worker slot
(``tidb_tpu.fabric.slot_of_conn_id``), which is how the bench attributes
per-process latency without any side channel.
"""

from __future__ import annotations

import socket
import struct

from ..server import protocol as P
from ..server.packet import PacketIO, read_lenenc_int, read_lenenc_str, \
    read_nul_str


class WireError(Exception):
    """Connection-level failure (classified clean by the bench: a killed
    worker's clients see exactly this, never a hang)."""


class FleetClient:
    def __init__(self, port: int, host: str = "127.0.0.1",
                 user: str = "root", password: str = "", db: str = "",
                 timeout: float = 30.0):
        try:
            self.sock = socket.create_connection((host, port),
                                                 timeout=timeout)
            self.io = PacketIO(self.sock)
            self.conn_id = self._handshake(user, password, db)
        except (OSError, ConnectionError) as e:
            raise WireError(f"connect {host}:{port}: {e}") from e

    @property
    def slot(self) -> "int | None":
        from . import slot_of_conn_id
        return slot_of_conn_id(self.conn_id)

    def host(self, hosts: int) -> "int | None":
        """The simulated host serving this connection, per the fleet's
        slot->host convention (fleet.Fleet.host_of: ``slot % hosts``) —
        how the bench proves a query landed on a SURVIVING host after a
        kill-host fault."""
        s = self.slot
        return None if s is None else s % max(int(hosts), 1)

    def _handshake(self, user, password, db) -> int:
        pkt = self.io.read_packet()
        if not pkt or pkt[0] != 10:
            raise WireError("bad handshake packet")
        _ver, pos = read_nul_str(pkt, 1)
        conn_id = struct.unpack_from("<I", pkt, pos)[0]
        pos += 4
        salt1 = pkt[pos:pos + 8]
        pos += 9
        pos += 2 + 1 + 2 + 2
        salt_len = pkt[pos]
        pos += 1 + 10
        salt2 = pkt[pos:pos + max(13, salt_len - 8) - 1]
        salt = salt1 + salt2
        caps = (P.CLIENT_PROTOCOL_41 | P.CLIENT_SECURE_CONNECTION
                | P.CLIENT_PLUGIN_AUTH | P.CLIENT_MULTI_RESULTS
                | (P.CLIENT_CONNECT_WITH_DB if db else 0))
        auth = P.native_password_hash(password.encode(), salt[:20])
        out = struct.pack("<I", caps) + struct.pack("<I", 1 << 24)
        out += bytes([255]) + b"\x00" * 23
        out += user.encode() + b"\x00"
        out += bytes([len(auth)]) + auth
        if db:
            out += db.encode() + b"\x00"
        out += b"mysql_native_password\x00"
        self.io.write_packet(out)
        resp = self.io.read_packet()
        if resp and resp[0] == 0xFF:
            code = struct.unpack_from("<H", resp, 1)[0]
            raise WireError(f"auth failed: {code} {resp[9:].decode()}")
        if not resp or resp[0] != 0x00:
            raise WireError("unexpected handshake response")
        return conn_id

    def query(self, sql: str):
        """-> ('ok', affected) | ('rows', (cols, rows)) | ('err', (code,
        msg)).  WireError on a dead connection (a killed worker)."""
        try:
            self.io.reset_seq()
            self.io.write_packet(bytes([P.COM_QUERY]) + sql.encode())
            return self._read_result()
        except (OSError, ConnectionError, IndexError, struct.error) as e:
            raise WireError(f"connection lost mid-query: "
                            f"{type(e).__name__}: {e}") from e

    def must_query(self, sql: str):
        kind, payload = self.query(sql)
        if kind == "err":
            raise WireError(f"query failed {payload[0]}: {payload[1]} "
                            f"({sql[:120]!r})")
        return payload if kind == "rows" else ([], [])

    def must_exec(self, sql: str):
        kind, payload = self.query(sql)
        if kind == "err":
            raise WireError(f"exec failed {payload[0]}: {payload[1]} "
                            f"({sql[:120]!r})")
        return payload

    def _read_result(self):
        first = self.io.read_packet()
        if first[0] == 0x00:
            affected, _pos = read_lenenc_int(first, 1)
            return "ok", affected
        if first[0] == 0xFF:
            code = struct.unpack_from("<H", first, 1)[0]
            return "err", (code, first[9:].decode(errors="replace"))
        ncols, _ = read_lenenc_int(first, 0)
        cols = []
        for _ in range(ncols):
            pkt = self.io.read_packet()
            pos = 0
            vals = []
            for _f in range(6):
                v, pos = read_lenenc_str(pkt, pos)
                vals.append(v)
            cols.append(vals[4].decode())
        eof = self.io.read_packet()
        if eof[0] != 0xFE:
            raise WireError("missing column EOF")
        rows = []
        while True:
            pkt = self.io.read_packet()
            if pkt[0] == 0xFE and len(pkt) < 9:
                break
            pos = 0
            row = []
            for _ in range(ncols):
                if pkt[pos] == 0xFB:
                    row.append(None)
                    pos += 1
                else:
                    v, pos = read_lenenc_str(pkt, pos)
                    row.append(v.decode())
            rows.append(tuple(row))
        return "rows", (cols, rows)

    def close(self):
        try:
            self.io.reset_seq()
            self.io.write_packet(bytes([P.COM_QUIT]))
        except Exception:
            pass
        try:
            self.sock.close()
        except OSError:
            pass
