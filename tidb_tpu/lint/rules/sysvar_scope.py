"""Sysvar scope registry: process-wide knobs are read at GLOBAL scope,
per-session knobs at SESSION scope — statically enforced.

The PR 5 / PR 8 bug class: a sysvar that configures a PROCESS-WIDE
resource (the residency budget, the admission queue, the compile pool)
read through the session view lets one connection's session-scoped SET
reconfigure shared state out from under every other session
(`tidb_device_mem_budget` last-dispatcher-wins).  The inverse is as bad:
a per-session identity knob (`tidb_resource_group`) read from GLOBAL
scope makes every tenant the same tenant.

``SYSVAR_SCOPE`` below is the declared registry for the sysvars backing
the device serving stack; every ``tidb_device_*`` / ``tidb_compile_*``
sysvar read anywhere in the package MUST be declared here, and every
read site must request the declared scope:

  * a ``<x>.get_sysvar("name")`` call is a SESSION-scope read;
  * a ``<x>.global_vars.get("name", d)`` call (or through a local alias
    ``gv = dom.global_vars``) is a GLOBAL-scope read;
  * a local dispatcher closing over both (``src = lambda n, d:
    gv.get(n, d)`` in the Domain branch, ``ctx.get_sysvar`` in the bare
    fallback) is DUAL — global-first with the documented bare-context
    fallback, the sanctioned discipline for process knobs.

A session read of a process knob is allowed only in a function that
also performs the global read (the explicit Domain-first/bare-fallback
split, e.g. ``residency.attach``); a global or dual read of a session
knob is always a finding.
"""

from __future__ import annotations

import ast

from ..engine import Rule, register
from ._util import const_str, dotted

PROCESS, SESSION = "process", "session"

#: the declared scope of every sysvar backing the device serving stack.
#: PROCESS = the knob configures a process-wide shared resource (queue,
#: pool, ledger, breaker): reads go through the Domain's global_vars so
#: a session-scoped SET cannot reconfigure what other sessions share.
#: SESSION = the knob is per-connection (identity, per-statement
#: behavior): reads go through the session view.
SYSVAR_SCOPE = {
    # admission scheduler (executor/scheduler.py)
    "tidb_device_sched_queue_depth": PROCESS,
    "tidb_device_admission_timeout": PROCESS,
    "tidb_device_tenant_running_cap": PROCESS,
    "tidb_device_wfq_weights": PROCESS,
    # circuit breaker (executor/circuit.py)
    "tidb_device_circuit_threshold": PROCESS,
    "tidb_device_circuit_cooldown": PROCESS,
    # HBM residency ledger (ops/residency.py)
    "tidb_device_mem_budget": PROCESS,
    # compile service (executor/compile_service.py)
    "tidb_compile_workers": PROCESS,
    "tidb_compile_timeout": PROCESS,
    "tidb_compile_prewarm": PROCESS,
    # per-session knobs of the same stack
    "tidb_resource_group": SESSION,
    "tidb_compile_async": SESSION,
    "tidb_device_call_timeout": SESSION,
    "tidb_device_dispatch_rows": SESSION,
    "tidb_device_stream_rows": SESSION,
    "tidb_device_shape_buckets": SESSION,
    "tidb_device_compact": SESSION,
}

#: names outside the registry that still look like serving-stack knobs
#: must be declared (the registry is forced to stay current)
REQUIRED_PREFIXES = ("tidb_device_", "tidb_compile_")

#: the module that DEFINES the sysvar table (SysVar("name", scope, ...)
#: literals are declarations, not reads) and the SET/SHOW machinery that
#: legitimately touches both scopes of every variable
EXEMPT_FILES = {"session/sysvars.py", "session/session.py",
                "session/show.py", "session/memtables.py"}


def _read_sites(fn):
    """(name, kind, line) for every literal sysvar read in `fn`:
    kind session | global | dual."""
    # pass 1: local aliases of <x>.global_vars (alias collection must
    # finish before lambda classification — walk order is not source
    # order)
    gv_aliases = set()
    assigns = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.Assign):
            continue
        names = [t.id for t in node.targets if isinstance(t, ast.Name)]
        if not names:
            continue
        assigns.append((names, node.value))
        if dotted(node.value).endswith("global_vars"):
            gv_aliases.update(names)
    # pass 2: local dual dispatchers (name -> kinds its lambdas wrap)
    dispatchers: dict = {}
    for names, val in assigns:
        if not isinstance(val, ast.Lambda):
            continue
        kinds = set()
        for sub in ast.walk(val.body):
            if isinstance(sub, ast.Call):
                cn = dotted(sub.func)
                leaf = cn.rsplit(".", 1)[-1]
                if leaf == "get_sysvar":
                    kinds.add("session")
                elif leaf == "get" and (
                        "global_vars" in cn
                        or cn.split(".", 1)[0] in gv_aliases):
                    kinds.add("global")
        if kinds:
            d = dispatchers.setdefault(names[0], set())
            d.update(kinds)

    out = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        name = const_str(node.args[0])
        if name is None:
            continue
        cn = dotted(node.func)
        if not cn:
            continue
        leaf = cn.rsplit(".", 1)[-1]
        if leaf == "get_sysvar":
            out.append((name, "session", node.lineno))
        elif leaf == "get" and ("global_vars" in cn
                                or cn.split(".", 1)[0] in gv_aliases):
            out.append((name, "global", node.lineno))
        elif cn in dispatchers:
            kinds = dispatchers[cn]
            kind = "dual" if len(kinds) > 1 else next(iter(kinds))
            out.append((name, kind, node.lineno))
    return out


@register
class SysvarScope(Rule):
    name = "sysvar-scope"
    title = "sysvar reads request their declared process/session scope"

    def run(self, ctx):
        out = []
        seen: dict = {}

        def ident(base):
            k = seen.get(base, 0)
            seen[base] = k + 1
            return base + (f"#{k}" if k else "")

        for sf in ctx.package_files:
            if sf.rel in EXEMPT_FILES:
                continue
            # cheap text gate: no sysvar-read idiom, no AST walk
            if "get_sysvar" not in sf.text and "global_vars" not in sf.text:
                continue
            for node in ast.walk(sf.tree):
                if not isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                sites = _read_sites(node)
                if not sites:
                    continue
                qual = sf.qualname(node)
                global_read_names = {n for n, k, _l in sites
                                     if k in ("global", "dual")}
                for name, kind, line in sites:
                    scope = SYSVAR_SCOPE.get(name)
                    if scope is None:
                        if name.startswith(REQUIRED_PREFIXES):
                            out.append(self.finding(
                                sf.rel, line,
                                ident(f"undeclared:{name}@{qual}"),
                                f"sysvar {name} backs the device serving "
                                "stack but has no declared scope — add "
                                "it to lint/rules/sysvar_scope.py "
                                "SYSVAR_SCOPE as process or session"))
                        continue
                    if scope == PROCESS and kind == "session" \
                            and name not in global_read_names:
                        out.append(self.finding(
                            sf.rel, line,
                            ident(f"session-read:{name}@{qual}"),
                            f"{name} configures a process-wide resource "
                            "but is read through the session view: a "
                            "session-scoped SET would reconfigure "
                            "shared state (read the Domain's "
                            "global_vars, with get_sysvar only as the "
                            "bare-context fallback in the same "
                            "function)"))
                    elif scope == SESSION and kind in ("global", "dual"):
                        out.append(self.finding(
                            sf.rel, line,
                            ident(f"global-read:{name}@{qual}"),
                            f"{name} is per-session but is read at "
                            "GLOBAL scope — every connection would see "
                            "one shared value (read it via "
                            "ctx.get_sysvar)"))
        return out
