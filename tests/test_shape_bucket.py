"""Bucketed static shapes + compiled-fragment cache (the compile-amortization
layer): geometric row buckets (ops/device.py bucket_rows) pad device uploads
to canonical shapes with the live count traced, so a delta append, a second
table of similar size, or a different scale factor re-dispatches an already
compiled XLA program instead of re-tracing. Covers:

- the bucket policy itself (monotone, geometric, sysvar-disable),
- the recompile regression: a within-bucket delta performs ZERO new jax
  traces; crossing a bucket boundary performs exactly the expected ones,
- padding invariants: bucket-padding rows never appear in filter / join /
  agg / topk / window output, including nearly-all-padded edge buckets,
- the per-fragment-shape circuit breaker scope,
- the eval_scalar NEWDECIMAL-scale root fix (SET @r = 0.3 stays 0.3).
"""

import numpy as np
import pytest

from tidb_tpu.ops import device as dev
from tidb_tpu.executor.device_exec import pipe_cache_stats
from tidb_tpu.testkit import TestKit
from tidb_tpu.utils.chunk import Column


# ---------------------------------------------------------------------------
# bucket policy
# ---------------------------------------------------------------------------

class TestBucketPolicy:
    def test_monotone_and_covering(self):
        prev = 0
        for n in range(1, 5000, 7):
            b = dev.bucket_rows(n)
            assert b >= n
            assert b >= prev  # monotone in n
            prev = b

    def test_geometric_growth(self):
        # per_double=2 → powers of sqrt(2): padding overhead <= ~19%
        for n in (100, 10_000, 1_000_000):
            b = dev.bucket_rows(n, 2)
            assert b / n <= 2 ** 0.5 + 1e-9

    def test_bucket_count_per_doubling(self):
        # distinct buckets in [1024, 4096) == per_double * 2
        for per_double in (1, 2, 4):
            bs = {dev.bucket_rows(n, per_double)
                  for n in range(1025, 4097)}
            assert len(bs) == per_double * 2

    def test_disabled_returns_exact(self):
        assert dev.bucket_rows(12345, 0) == 12345

    def test_floor(self):
        assert dev.bucket_rows(1) == 8
        assert dev.bucket_rows(8) == 8
        assert dev.bucket_rows(9) == 12

    def test_pad_host(self):
        d = dev.pad_host(np.arange(5, dtype=np.int64), 8)
        assert d.shape == (8,) and (d[5:] == 0).all()
        nl = dev.pad_host(np.zeros(5, dtype=bool), 8, True)
        assert nl[5:].all() and not nl[:5].any()
        same = np.arange(5)
        assert dev.pad_host(same, 5) is not None
        assert len(dev.pad_host(same, 3)) == 5  # never truncates


# ---------------------------------------------------------------------------
# recompile regression: one compile per bucket, zero per within-bucket delta
# ---------------------------------------------------------------------------

def _install_fact(tk, table, n, n_keys=50, db="test"):
    """Bulk-install a fact-shaped table (a pk handle, k FK, v value,
    s dict string) — values bounded so delta rows can stay in-range."""
    tk.must_exec(f"create table {table} (a bigint primary key, k bigint, "
                 "v bigint, s varchar(8))")
    info = tk.session.infoschema().table_by_name(db, table)
    rng = np.random.default_rng(7)
    cols = {c.name: c for c in info.public_columns()}
    sdict = np.array([b"xx", b"yy", b"zz"], dtype=object)
    codes = rng.integers(0, 3, n).astype(np.int64)
    scol = Column(cols["s"].ftype, sdict[codes], np.zeros(n, dtype=bool))
    scol.set_dict(codes.astype(np.int32), sdict)
    columns = {
        cols["a"].id: Column(cols["a"].ftype, np.arange(1, n + 1)),
        cols["k"].id: Column(cols["k"].ftype,
                             rng.integers(1, n_keys + 1, n)),
        cols["v"].id: Column(cols["v"].ftype, rng.integers(0, 101, n)),
        cols["s"].id: scol,
    }
    tk.domain.columnar_cache.install_bulk(
        info, columns, np.arange(1, n + 1, dtype=np.int64))
    return info


def _traces():
    return pipe_cache_stats()["traces"]


class TestRecompileRegression:
    """The tentpole's measurable promise (fixed-seed compile-cache smoke):
    repeated runs with growing deltas re-trace once per BUCKET, not once
    per row count."""

    def test_agg_zero_recompile_within_bucket(self):
        tk = TestKit()
        _install_fact(tk, "b1", 2000)
        tk.must_exec("set tidb_executor_engine = 'tpu'")
        q = ("select s, sum(v), count(*) from b1 where v >= 10 "
             "group by s order by s")
        cold = tk.must_query(q).rows
        t0 = _traces()
        assert tk.must_query(q).rows == cold  # steady re-run
        assert _traces() == t0, "re-run of identical data re-traced"
        # within-bucket delta: 2000 → 2002 stays inside bucket 2048;
        # values/strings inside existing ranges so packs and dictionary
        # content are stable
        tk.must_exec("insert into b1 values (2001, 5, 50, 'xx'), "
                     "(2002, 6, 7, 'yy')")
        rows = tk.must_query(q).rows
        assert rows != cold  # the delta is visible...
        assert _traces() == t0, \
            "within-bucket delta append forced an XLA re-trace"

    def test_agg_one_recompile_per_bucket_crossing(self):
        tk = TestKit()
        _install_fact(tk, "b2", 2040)
        tk.must_exec("set tidb_executor_engine = 'tpu'")
        q = "select s, sum(v) from b2 group by s order by s"
        tk.must_query(q)
        t0 = _traces()
        # 2040 → 2100 crosses bucket 2048 → 2897: exactly one new program
        vals = ", ".join(f"({2040 + i}, 1, 1, 'zz')" for i in range(1, 61))
        tk.must_exec(f"insert into b2 values {vals}")
        tk.must_query(q)
        t1 = _traces()
        assert t1 > t0, "bucket crossing must compile the new shape"
        # further within-(new-)bucket deltas: no more traces
        tk.must_exec("insert into b2 values (9001, 2, 3, 'xx')")
        tk.must_query(q)
        assert _traces() == t1

    def test_join_fragment_zero_recompile_within_bucket(self):
        tk = TestKit()
        _install_fact(tk, "jf", 2000)
        tk.must_exec("create table jd (k bigint primary key, g varchar(8))")
        for i in range(1, 51):
            tk.must_exec(f"insert into jd values ({i}, 'g{i % 5}')")
        tk.must_exec("set tidb_executor_engine = 'tpu'")
        q = ("select jd.g, sum(jf.v) from jf join jd on jf.k = jd.k "
             "group by jd.g order by jd.g")
        cold = tk.must_query(q).rows
        # second run may legitimately compile ONCE more: the learned-size
        # store (_CAP_STORE) jumps to tight capacities discovered by the
        # first run — the documented once-per-fragment-ever discovery
        assert tk.must_query(q).rows == cold
        t0 = _traces()
        assert tk.must_query(q).rows == cold  # steady state
        assert _traces() == t0
        # delta on the FACT side only: the dims (and their join indexes)
        # are untouched, the fact re-encodes to identical dictionary
        # content and the same bucket → compiled fragment reused
        tk.must_exec("insert into jf values (2001, 5, 50, 'xx')")
        assert tk.must_query(q).rows != cold
        assert _traces() == t0, \
            "fact-side within-bucket delta re-traced the join fragment"

    def test_build_side_delta_zero_recompile_within_bucket(self):
        """The LAST recompile trigger (ROADMAP item 1): a build-side
        INSERT changes the join index's row count — n_valid now rides as
        a TRACED scalar over bucket-padded index arrays, so a
        within-bucket (and within-quantized-pack-range) build delta
        rebuilds only the cheap numpy index and reuses the compiled
        fragment."""
        tk = TestKit()
        _install_fact(tk, "jb", 2000, n_keys=50)
        # SPARSE dim keys (2..100 even): a later odd-key INSERT stays
        # inside the quantized pack range AND keeps the build unique
        tk.must_exec("create table jbd (k bigint primary key, "
                     "g varchar(8))")
        for i in range(1, 51):
            tk.must_exec(f"insert into jbd values ({2 * i}, 'g{i % 5}')")
        tk.must_exec("set tidb_executor_engine = 'tpu'")
        q = ("select jbd.g, sum(jb.v) from jb join jbd on jb.k = jbd.k "
             "group by jbd.g order by jbd.g")
        cold = tk.must_query(q).rows
        assert tk.must_query(q).rows == cold  # learned-size settle
        t0 = _traces()
        assert tk.must_query(q).rows == cold  # steady state
        assert _traces() == t0
        # BUILD-side delta: key 31 is absent, odd, inside [2,100] (the
        # quantized pack range), 'g1' already in the dictionary; 50→51
        # index entries stays inside the rows bucket (64) and the leaf
        # bucket — the index rebuilds host-side, the program re-dispatches
        tk.must_exec("insert into jbd values (31, 'g1')")
        host = None
        try:
            tk.must_exec("set tidb_executor_engine = 'host'")
            host = tk.must_query(q).rows
        finally:
            tk.must_exec("set tidb_executor_engine = 'tpu'")
        got = tk.must_query(q).rows
        assert got == host and got != cold
        assert _traces() == t0, \
            "build-side within-bucket delta re-traced the join fragment"


# ---------------------------------------------------------------------------
# padding invariants: padded rows never escape
# ---------------------------------------------------------------------------

def _parity(tk, q):
    tk.must_exec("set tidb_executor_engine = 'tpu'")
    d = tk.must_query(q).rows
    tk.must_exec("set tidb_executor_engine = 'host'")
    h = tk.must_query(q).rows
    tk.must_exec("set tidb_executor_engine = 'auto'")
    assert d == h, f"device/host divergence for {q!r}: {d} vs {h}"
    return d


class TestPaddingInvariants:
    @pytest.fixture()
    def tk(self):
        tk = TestKit()
        tk.must_exec("create table p (a bigint primary key, k bigint, "
                     "v bigint, s varchar(8))")
        # n=9 → bucket 12: three padding rows in every upload
        for i in range(1, 10):
            tk.must_exec(f"insert into p values ({i}, {i % 3}, {i * 10}, "
                         f"'s{i % 2}')")
        return tk

    def test_unfiltered_count(self, tk):
        # no WHERE at all: only the n_live mask stands between the padding
        # and the count
        assert _parity(tk, "select count(*) from p") == [("9",)]

    def test_unfiltered_sum_min_max(self, tk):
        _parity(tk, "select sum(v), min(v), max(v), avg(v) from p")

    def test_filter_and_group(self, tk):
        _parity(tk, "select k, count(*), sum(v) from p where v >= 20 "
                    "group by k order by k")

    def test_string_group_keys(self, tk):
        _parity(tk, "select s, count(*) from p group by s order by s")

    def test_topk(self, tk):
        _parity(tk, "select k, sum(v) from p group by k "
                    "order by 2 desc limit 2")

    def test_count_distinct(self, tk):
        _parity(tk, "select k, count(distinct v) from p group by k "
                    "order by k")

    def test_join(self, tk):
        tk.must_exec("create table pd (k bigint primary key, nm varchar(8))")
        for i in range(3):
            tk.must_exec(f"insert into pd values ({i}, 'n{i}')")
        _parity(tk, "select pd.nm, sum(p.v) from p join pd on p.k = pd.k "
                    "group by pd.nm order by pd.nm")

    def test_window(self, tk):
        _parity(tk, "select a, k, row_number() over "
                    "(partition by k order by v desc), "
                    "sum(v) over (partition by k order by v) "
                    "from p order by a")

    def test_window_no_columns(self, tk):
        # count(*) OVER () reads no columns at all: the device program's
        # env is empty and the row count must come from the plan, not an
        # env array (code-review regression)
        _parity(tk, "select a, count(*) over () from p order by a")

    def test_single_row_edge_bucket(self):
        # n=1 in bucket 8: nearly every row of the upload is padding
        tk = TestKit()
        tk.must_exec("create table e1 (a bigint primary key, v bigint)")
        tk.must_exec("insert into e1 values (1, 42)")
        assert _parity(tk, "select count(*), sum(v) from e1") \
            == [("1", "42")]
        _parity(tk, "select v, count(*) from e1 group by v")

    def test_all_nulls_edge_bucket(self):
        # padding rows are null-masked; real NULL rows must still group
        # apart from padding
        tk = TestKit()
        tk.must_exec("create table e2 (a bigint primary key, v bigint)")
        for i in range(1, 10):
            tk.must_exec(f"insert into e2 values ({i}, null)")
        assert _parity(tk, "select count(*), count(v) from e2") \
            == [("9", "0")]
        _parity(tk, "select v, count(*) from e2 group by v")


# ---------------------------------------------------------------------------
# per-fragment-shape circuit breaker scope
# ---------------------------------------------------------------------------

class TestBreakerShapeScope:
    def test_one_shape_cools_down_alone(self):
        from tidb_tpu.executor.circuit import get_breaker
        from tidb_tpu.executor.device_exec import (run_device,
                                                   DeviceUnsupported)
        tk = TestKit()
        br = get_breaker(tk.session, shape="join")
        for _ in range(br.threshold):
            br.record_failure(RuntimeError("XlaRuntimeError: boom"))
        assert br.snapshot()["state"] == "open"
        assert get_breaker(tk.session, shape="agg").snapshot()["state"] \
            == "closed"
        # join fragments degrade, agg fragments keep running on-device
        with pytest.raises(DeviceUnsupported):
            run_device(tk.session, lambda: 1, shape="join")
        assert run_device(tk.session, lambda: 1, shape="agg") == 1

    def test_snapshot_names_shape(self):
        from tidb_tpu.executor.circuit import CircuitBreaker
        assert CircuitBreaker(shape="window").snapshot()["shape"] \
            == "window"


# ---------------------------------------------------------------------------
# eval_scalar NEWDECIMAL scale (root-cause fix)
# ---------------------------------------------------------------------------

class TestEvalScalarDecimal:
    def test_user_var_decimal_literal(self):
        tk = TestKit()
        tk.must_exec("set @r = 0.3")
        assert tk.must_query("select @r").rows == [("0.3",)]

    def test_user_var_negative_decimal(self):
        tk = TestKit()
        tk.must_exec("set @x = -0.5")
        assert tk.must_query("select @x").rows == [("-0.5",)]

    def test_user_var_decimal_expression(self):
        tk = TestKit()
        tk.must_exec("set @s = 1.25 + 0.25")
        assert tk.must_query("select @s").rows == [("1.50",)]

    def test_user_var_in_comparison(self):
        tk = TestKit()
        tk.must_exec("create table ud (v decimal(5,2))")
        tk.must_exec("insert into ud values (0.25), (0.35)")
        tk.must_exec("set @r = 0.3")
        assert tk.must_query(
            "select v from ud where v > @r").rows == [("0.35",)]

    def test_sysvar_decimal(self):
        tk = TestKit()
        tk.must_exec("set global tidb_auto_analyze_ratio = 0.3")
        assert tk.must_query(
            "select @@global.tidb_auto_analyze_ratio").rows == [("0.3",)]

    def test_column_default_decimal_scale(self):
        tk = TestKit()
        tk.must_exec("create table dd (a decimal(5,2) default 1.5, "
                     "b int)")
        tk.must_exec("insert into dd (b) values (1)")
        assert tk.must_query("select a from dd").rows == [("1.50",)]

    def test_internal_repr_unchanged_for_dml(self):
        tk = TestKit()
        tk.must_exec("create table di (a decimal(7,3))")
        tk.must_exec("insert into di values (2.345), (-0.5)")
        assert tk.must_query("select a from di order by a").rows \
            == [("-0.500",), ("2.345",)]
