"""Round-5 breadth: new builtins + sysvars behave, not just register
(reference: expression/builtin.go:573 registry, sessionctx/variable/
sysvar.go)."""

import json

import pytest

from tidb_tpu.testkit import TestKit


@pytest.fixture(scope="module")
def tk():
    tk = TestKit()
    tk.must_exec("use test")
    return tk


class TestNewBuiltins:
    def test_vitess_hash(self, tk):
        # vitess' published shard-hash vectors (util/vitess/vitess_hash.go:
        # DES-ECB, null key, big-endian uint64)
        assert tk.must_query("select vitess_hash(1)").rows == [
            ("1615456034434468822",)]  # 0x166b40b44aba4bd6
        assert tk.must_query("select vitess_hash(0)").rows == [
            ("10134873677816210343",)]  # uint64 render, not negative
        assert tk.must_query("select vitess_hash(null)").rows == [(None,)]

    def test_encode_decode_roundtrip(self, tk):
        rows = tk.must_query(
            "select decode(encode('secret stuff', 'pw'), 'pw'),"
            " encode('abc', 'k') = 'abc'").rows
        assert rows == [("secret stuff", "0")]
        assert tk.must_query(
            "select decode(null, 'pw'), encode('a', null)").rows == [
                (None, None)]

    def test_current_role_without_set_role(self, tk):
        assert tk.must_query("select current_role()").rows == [("NONE",)]

    def test_default_func_in_select(self, tk):
        # reference: expression_rewriter.go evalDefaultExpr — the
        # column's catalog default as a constant; NOT NULL without a
        # default errors 1364
        tk.must_exec("create table dft (a int default 7, "
                     "b varchar(6) default 'x', c int, d int not null)")
        tk.must_exec("insert into dft values (1,'y',2,3)")
        assert tk.must_query(
            "select default(a), default(b), default(c) from dft"
        ).rows == [("7", "x", None)]
        e = tk.exec_error("select default(d) from dft")
        assert getattr(e, "code", None) == 1364
        tk.must_exec("drop table dft")

    def test_default_func_alias_and_named_col(self, tk):
        # an alias shadowing a real table must NOT leak that table's
        # default (origin-table resolution); mixed-case column names
        # resolve; DEFAULT(named) in INSERT/UPDATE uses the NAMED
        # column's default, not the assignment target's
        tk.must_exec("create table du (a int default 1)")
        tk.must_exec("create table dv (Abc int default 2)")
        tk.must_exec("insert into dv values (9)")
        assert tk.must_query(
            "select default(Abc) from dv as du").rows == [("2",)]
        tk.must_exec("create table dt2 (a int default 5, b int default 8)")
        tk.must_exec("insert into dt2 (a, b) values (default(b), 1)")
        tk.must_query("select * from dt2").check([("8", "1")])
        tk.must_exec("update dt2 set a = default(b)")
        tk.must_query("select * from dt2").check([("8", "1")])
        tk.must_exec("update dt2 set a = default")
        tk.must_query("select * from dt2").check([("5", "1")])
        for t in ("du", "dv", "dt2"):
            tk.must_exec(f"drop table {t}")

    def test_translate(self, tk):
        assert tk.must_query(
            "select translate('abcab', 'ab', 'xy')").rows == [("xycxy",)]
        # from-chars beyond the to-string are deleted (Oracle semantics)
        assert tk.must_query(
            "select translate('abc', 'abc', 'x')").rows == [("x",)]
        assert tk.must_query(
            "select translate(null, 'a', 'b')").rows == [(None,)]

    def test_translate_first_occurrence_wins(self, tk):
        """Duplicate chars in `from`: the FIRST mapping applies
        (regression: int/str key mismatch made the last win)."""
        assert tk.must_query(
            "select translate('a', 'aa', 'xy')").rows == [("x",)]

    def test_temporal_binary_arithmetic(self, tk):
        assert tk.must_query(
            "select date('2024-01-10') - interval 3 day").rows == \
            [("2024-01-07",)]
        assert tk.must_query(
            "select interval 1 day + date('2024-01-10')").rows == \
            [("2024-01-11",)]

    def test_character_length_alias(self, tk):
        assert tk.must_query(
            "select character_length('héllo')").rows == [("5",)]

    def test_istrue_with_null(self, tk):
        assert tk.must_query(
            "select istrue_with_null(null), istrue_with_null(2), "
            "istrue_with_null(0)").rows == [(None, "1", "0")]

    def test_session_user_schema_aliases(self, tk):
        u, s = tk.must_query("select session_user(), schema()").rows[0]
        assert "@" in u and s == "test"

    def test_decode_sql_digests_roundtrip(self, tk):
        tk.must_query("select 42")
        dg = tk.must_query(
            "select tidb_encode_sql_digest('select 42')").rows[0][0]
        out = tk.must_query(
            f"select tidb_decode_sql_digests('[\"{dg}\", \"missing\"]')"
        ).rows[0][0]
        decoded = json.loads(out)
        assert decoded[0] is not None and "42" in decoded[0]
        assert decoded[1] is None

    def test_bounded_staleness_clamps(self, tk):
        v = tk.must_query("select tidb_bounded_staleness("
                          "'2020-01-01', '2020-01-02')").rows[0][0]
        assert v.startswith("2020-01-02")  # now() clamps to the upper bound

    def test_registry_count(self, tk):
        from tidb_tpu.expression.builtins_ext import _DISPATCH
        assert len(_DISPATCH) >= 256


class TestPlacementPolicies:
    """Placement policy DDL (reference: ddl/placement_policy.go) —
    catalog-persisted; with one embedded store the constraints are
    metadata, not scheduling."""

    def test_create_alter_drop_roundtrip(self, tk):
        from tidb_tpu.errors import TiDBError, ErrCode
        tk.must_exec("create placement policy pp1 "
                     "primary_region='us-east-1' "
                     "regions='us-east-1,us-west-1' followers=2")
        rows = tk.must_query(
            "select policy_name, primary_region, followers from "
            "information_schema.placement_policies").rows
        assert ("pp1", "us-east-1", "2") in rows
        tk.must_exec("alter placement policy pp1 followers=4")
        rows = tk.must_query(
            "select followers from information_schema.placement_policies "
            "where policy_name = 'pp1'").rows
        assert rows == [("4",)]
        with pytest.raises(TiDBError) as ei:
            tk.must_exec("create placement policy pp1 followers=1")
        assert ei.value.code == ErrCode.PlacementPolicyExists
        tk.must_exec("create placement policy if not exists pp1 "
                     "followers=1")  # no-op
        tk.must_exec("drop placement policy pp1")
        tk.must_exec("drop placement policy if exists pp1")
        with pytest.raises(TiDBError) as ei:
            tk.must_exec("drop placement policy pp1")
        assert ei.value.code == ErrCode.PlacementPolicyNotExists

    def test_policies_survive_reload(self, tk):
        tk.must_exec("create placement policy pp2 constraints="
                     "'[+disk=ssd]'")
        tk.domain.reload_schema()
        rows = tk.must_query(
            "select constraints from information_schema."
            "placement_policies where policy_name = 'pp2'").rows
        assert rows == [("[+disk=ssd]",)]
        tk.must_exec("drop placement policy pp2")


class TestGBK:
    """gbk charset + gbk_bin / gbk_chinese_ci collations (reference:
    parser/charset/, util/collate/gbk_chinese_ci.go, gbk_bin.go)."""

    def test_gbk_chinese_ci_hanzi_order(self, tk):
        tk.must_exec("create table gh (s varchar(10) collate "
                     "gbk_chinese_ci)")
        for ch in ("从", "啊", "吧"):
            tk.must_exec(f"insert into gh values ('{ch}')")
        rows = [r[0] for r in
                tk.must_query("select s from gh order by s").rows]
        # GBK code order sorts roughly by pinyin: 啊(a) < 吧(ba) < 从(cong)
        assert rows == ["啊", "吧", "从"]
        # utf8 byte order would be 从 < 吧 < 啊 — must NOT be that
        assert rows != ["从", "吧", "啊"]

    def test_gbk_ci_case_folds_bin_does_not(self, tk):
        tk.must_exec("create table gc (s varchar(10) collate "
                     "gbk_chinese_ci, b varchar(10) collate gbk_bin)")
        tk.must_exec("insert into gc values ('Ab', 'Ab'), ('aB', 'aB')")
        assert tk.must_query(
            "select count(*) from gc where s = 'AB'").rows == [("2",)]
        assert tk.must_query(
            "select count(*) from gc where b = 'AB'").rows == [("0",)]
        assert tk.must_query(
            "select count(distinct s) from gc").rows == [("1",)]
        assert tk.must_query(
            "select count(distinct b) from gc").rows == [("2",)]

    def test_table_default_charset_gbk(self, tk):
        tk.must_exec("create table gt (s varchar(10)) charset = gbk")
        info = tk.domain.infoschema().table_by_name("test", "gt")
        assert info.columns[0].ftype.collate == "gbk_chinese_ci"

    def test_show_includes_gbk(self, tk):
        cs = {r[0] for r in tk.must_query("show character set").rows}
        assert "gbk" in cs
        col = {r[0] for r in tk.must_query("show collation").rows}
        assert {"gbk_chinese_ci", "gbk_bin"} <= col


class TestNewSysvars:
    def test_registry_count(self, tk):
        from tidb_tpu.session import sysvars
        assert len(sysvars.get_registry()) >= 248  # reference has 248

    def test_last_txn_info_records_commit(self, tk):
        tk.must_exec("create table lti (a bigint)")
        tk.must_exec("insert into lti values (1)")
        info = json.loads(
            tk.must_query("select @@tidb_last_txn_info").rows[0][0])
        assert info["commit_ts"] > info["start_ts"] > 0

    def test_use_plan_baselines_gates_binding_match(self, tk):
        tk.must_exec("create table pbl (a bigint, b bigint, index ia (a))")
        tk.must_exec("create session binding for select * from pbl "
                     "where a = 1 using select * from pbl use index (ia) "
                     "where a = 1")
        tk.must_query("select * from pbl where a = 1")
        assert tk.session.binding_used is not None
        tk.must_exec("set tidb_use_plan_baselines = OFF")
        tk.must_query("select * from pbl where a = 1")
        assert tk.session.binding_used is None
        tk.must_exec("set tidb_use_plan_baselines = ON")

    def test_bare_word_enum_set(self, tk):
        tk.must_exec("set tidb_partition_prune_mode = dynamic")
        assert tk.must_query(
            "select @@tidb_partition_prune_mode").rows == [("dynamic",)]
        tk.must_exec("set tidb_partition_prune_mode = static")

    def test_enum_validation_rejects_garbage(self, tk):
        from tidb_tpu.errors import TiDBError
        with pytest.raises(TiDBError):
            tk.must_exec("set tidb_read_consistency = 'bogus'")
