"""MPP SQL execution: fused scan/join/agg fragments run SPMD over a
device mesh — the reference's MPP fragment execution wired into the SQL
path (planner/core/fragment.go cuts plans at exchange boundaries;
store/copr/mpp.go:65 constructs per-node tasks; executor/mpp_gather.go
streams fragments back; unistore/cophandler/mpp_exec.go runs them).

TPU-native translation: one `shard_map`-jitted SPMD program per fragment.
- The probe-spine fact table is row-sharded over the mesh axis (the
  reference's region sharding, §2.2 DP); every dimension table is
  replicated (broadcast hash join — the PhysicalExchangeSender Broadcast
  type).
- Each shard runs the SAME fused scan→filter→join→partial-agg body the
  single-chip path compiles (device_join.compile_fragment), producing a
  `capacity`-bounded partial aggregate state.
- Exchange = `all_gather` of the bounded partial states over ICI; the
  final merge is simply a second `_agg_impl` over the gathered partials
  (partial/final parallel hash agg, executor/aggregate.go:85-165),
  replicated on every shard. No host hop anywhere inside the fragment.

Static shapes throughout: join expansions and agg states are capacity-
bounded with overflow flags `pmax`-reduced across the mesh; the host
retries with doubled capacities — one extra compile, never wrong results.
"""

from __future__ import annotations

import collections

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..utils.jaxcompat import shard_map

from ..ops import device as dev
from ..ops.device import DeviceUnsupported
from .device_exec import (
    _assemble_agg, _estimate_groups, _pipe_cache_get, _pipe_cache_put,
    _plan_agg, engine_mode)
from .device_join import (
    _JoinNode, _Leaf, _combined_join_keys, _global_dcols, _join_expand,
    _leaf_env, _shift_expr, collect_tree, fragment_sig)

AXIS = "part"

#: merge op per partial op for the final stage: partial counts re-sum,
#: partial sums re-sum, min/max merge with themselves, first takes any
_MERGE_OP = {"count": "sum_i", "sum_i": "sum_i", "sum_f": "sum_f",
             "min": "min", "max": "max", "first": "first"}

#: observability: fragments actually executed through the mesh path
MPP_STATS = {"fragments": 0, "retries": 0, "shuffle_joins": 0,
             "skew_broadcasts": 0, "exchange_retries": 0}

_MESH_CACHE: dict[int, object] = {}


def mpp_mesh(ctx):
    """The session's mesh, or None when the MPP engine isn't selected.
    `tidb_mpp_devices` = 0 means every visible device."""
    if engine_mode(ctx) != "tpu-mpp":
        return None
    try:
        n = int(ctx.get_sysvar("tidb_mpp_devices"))
    except Exception:
        n = 0
    ndev = len(jax.devices())
    if n <= 0:
        n = ndev
    n = min(n, ndev)
    if n < 2:
        return None  # nothing to distribute over
    mesh = _MESH_CACHE.get(n)
    if mesh is None:
        from ..parallel import make_mesh
        mesh = make_mesh(n, axis=AXIS)
        _MESH_CACHE[n] = mesh
    return mesh


# ---------------------------------------------------------------------------
# mesh placement cache (the HBM-resident working set, per mesh)
# ---------------------------------------------------------------------------

#: (id(src_data), id(mesh), sharded) → (placed_data, placed_nulls, src_refs)
#: src_refs pins the source arrays so ids stay unique while cached
_PLACE_CACHE: "collections.OrderedDict" = collections.OrderedDict()
_PLACE_CACHE_MAX = 128


def _place_col(data, nulls, mesh, sharded, n_shards):
    key = (id(data), id(mesh), sharded)
    hit = _PLACE_CACHE.get(key)
    if hit is not None:
        _PLACE_CACHE.move_to_end(key)
        return hit[0], hit[1]
    if sharded:
        d = np.asarray(data)
        nl = np.asarray(nulls)
        pad = (-d.shape[0]) % n_shards
        if pad:
            d = np.concatenate([d, np.zeros(pad, dtype=d.dtype)])
            nl = np.concatenate([nl, np.ones(pad, dtype=bool)])
        spec = NamedSharding(mesh, P(AXIS))
        out = (jax.device_put(d, spec), jax.device_put(nl, spec))
    else:
        spec = NamedSharding(mesh, P())
        out = (jax.device_put(data, spec), jax.device_put(nulls, spec))
    _PLACE_CACHE[key] = (out[0], out[1], (data, nulls))
    while len(_PLACE_CACHE) > _PLACE_CACHE_MAX:
        _PLACE_CACHE.popitem(last=False)
    return out


def _valid_array(n_rows, mesh, n_shards):
    """Row-validity for the sharded leaf (False on the pad tail)."""
    pad = (-n_rows) % n_shards
    v = np.ones(n_rows + pad, dtype=bool)
    if pad:
        v[n_rows:] = False
    return jax.device_put(v, NamedSharding(mesh, P(AXIS)))


# ---------------------------------------------------------------------------
# hash-shuffle exchange (the Hash exchange type — reference:
# planner/core/fragment.go:37,64 ExchangeSender{HashPartition},
# store/copr/mpp.go:65; here: in-body bucketize + lax.all_to_all over ICI)
# ---------------------------------------------------------------------------

def _mix64(k):
    """murmur3 fmix64 over int64 lanes — decorrelates FK-stride keys from
    the mod-n_shards destination (the reference hashes partition keys with
    murmur, unistore/cophandler/mpp_exec.go)."""
    u = k.astype(jnp.uint64)
    u = u ^ (u >> 33)
    u = u * jnp.uint64(0xFF51AFD7ED558CCD)
    u = u ^ (u >> 33)
    u = u * jnp.uint64(0xC4CEB9FE1A85EC53)
    u = u ^ (u >> 33)
    return u


def _dest_hash(key_ds, n_shards):
    """Destination shard per row from the (multi-)column join key. Both
    join sides use the same fold, so equal keys land on the same shard."""
    h = jnp.zeros(key_ds[0].shape[0], dtype=jnp.uint64)
    for d in key_ds:
        h = _mix64(h ^ _mix64(d.astype(jnp.int64)))
    return (h % jnp.uint64(n_shards)).astype(jnp.int32)


def _exchange_leaf(col_pairs, dest, valid, n_shards, cap):
    """Repartition one leaf's per-shard rows by `dest`: sort-based
    bucketize (gather formulation — no scatter) into n_shards buckets of
    `cap` slots, then one tiled all_to_all per column so each shard ends
    up holding exactly the rows hashed to it.

    col_pairs: [(data, nulls)] local slices; returns (new_col_pairs,
    new_valid, overflow) with n_shards*cap rows per shard."""
    m = valid.shape[0]
    dest = jnp.where(valid, dest, n_shards)       # invalid rows sort last
    order = jnp.argsort(dest)
    sd = dest[order]
    shard_ids = jnp.arange(n_shards, dtype=sd.dtype)
    starts = jnp.searchsorted(sd, shard_ids, side="left")
    cnt = jnp.searchsorted(sd, shard_ids, side="right") - starts
    ovf = jnp.any(cnt > cap)
    d_grid = jnp.repeat(shard_ids, cap)
    c_grid = jnp.tile(jnp.arange(cap, dtype=sd.dtype), n_shards)
    src = jnp.clip(starts[d_grid] + c_grid, 0, jnp.maximum(m - 1, 0))
    rows = order[src]
    slot_valid = c_grid < cnt[d_grid]

    def x(a):
        return jax.lax.all_to_all(a, AXIS, 0, 0, tiled=True)

    out_cols = [(x(d[rows]), x(nl[rows])) for d, nl in col_pairs]
    return out_cols, x(slot_valid), ovf


# ---------------------------------------------------------------------------
# the SPMD fragment program
# ---------------------------------------------------------------------------

def _build_mpp_pipeline(mesh, leaves, joins, root, sharded_ids, leaf_cond_fns,
                        cond_fns, key_fns, n_keys, val_plan, agg_ops,
                        capacity, key_pack, env_specs, shuffle=None):
    """shard_map + jit the whole fragment: per-shard fused body → partial
    agg → all_gather → replicated final merge. Same body structure as
    device_join.compile_fragment but per-shard shapes come from the traced
    env and each sharded leaf ANDs its validity mask.

    shuffle: None (broadcast join) or (node, left_leaf, right_leaf,
    cap_l, cap_r) — hash-repartition BOTH sides of `node` by join key
    over the mesh before the local join (the Hash exchange type)."""
    merge_ops = tuple(_MERGE_OP[o] for o in agg_ops)
    n_joins = len(joins)
    n_shards = mesh.shape[AXIS]
    n_xovf = 2 if shuffle is not None else 0

    def body(env, svalids):
        overflows = []
        span_ovfs = []
        env = dict(env)
        leaf_valid = dict(zip(sharded_ids, svalids))
        conds_consumed = set()
        xovfs = []
        if shuffle is not None:
            node, llid, rlid, cap_l, cap_r = shuffle
            for leaf_id, kfns, xcap in ((llid, node._lk_fns, cap_l),
                                        (rlid, node._rk_fns, cap_r)):
                leaf = leaves[leaf_id]
                n = env[leaf.offset][0].shape[0]
                valid = leaf_valid.get(leaf_id, jnp.ones(n, dtype=bool))
                # pre-exchange filter: leaf conds cut exchange volume
                for f in leaf_cond_fns[leaf_id]:
                    d, nl = f(env)
                    valid = valid & jnp.broadcast_to((d != 0) & ~nl, (n,))
                conds_consumed.add(leaf_id)
                kds, knulls = zip(*[dev.broadcast_1d(*f(env), n)
                                    for f in kfns])
                for nl in knulls:
                    valid = valid & ~nl    # null keys never match: drop
                dest = _dest_hash(kds, n_shards)
                cols = [env[leaf.offset + i] for i in range(leaf.ncols)]
                out_cols, out_valid, ovf = _exchange_leaf(
                    cols, dest, valid, n_shards, xcap)
                for i in range(leaf.ncols):
                    env[leaf.offset + i] = out_cols[i]
                leaf_valid[leaf_id] = out_valid
                xovfs.append(ovf)

        def leaf_rel(leaf):
            n = env[leaf.offset][0].shape[0]
            mask = leaf_valid.get(leaf.leaf_id)
            if mask is None:
                mask = jnp.ones(n, dtype=bool)
            if leaf.leaf_id not in conds_consumed:
                for f in leaf_cond_fns[leaf.leaf_id]:
                    d, nl = f(env)
                    mask = mask & jnp.broadcast_to((d != 0) & ~nl, (n,))
            return {leaf.leaf_id: jnp.arange(n)}, mask

        def gather_env(idxmap, node):
            out = {}
            for leaf in leaves:
                if leaf.leaf_id in idxmap:
                    if not (node.offset <= leaf.offset
                            < node.offset + node.ncols):
                        continue
                    idx = idxmap[leaf.leaf_id]
                    for i in range(leaf.ncols):
                        d, nl = env[leaf.offset + i]
                        out[leaf.offset + i] = (d[idx], nl[idx])
            return out

        def eval_node(node):
            if isinstance(node, _Leaf):
                return leaf_rel(node)
            lidx, lvalid = eval_node(node.left)
            ridx, rvalid = eval_node(node.right)
            lenv = gather_env(lidx, node.left)
            renv = gather_env(ridx, node.right)
            lkds, lknulls = zip(*[
                dev.broadcast_1d(*f(lenv), lvalid.shape[0])
                for f in node._lk_fns])
            rkds, rknulls = zip(*[
                dev.broadcast_1d(*f(renv), rvalid.shape[0])
                for f in node._rk_fns])
            pk_d, pvalid, bk_d, bvalid, sovf = _combined_join_keys(
                lkds, lknulls, lvalid, rkds, rknulls, rvalid)
            span_ovfs.append(sovf)
            pi, bi, valid, ovf = _join_expand(
                bk_d, bvalid, pk_d, pvalid, node.cap)
            overflows.append(ovf)
            idxmap = {k: v[pi] for k, v in lidx.items()}
            idxmap.update({k: v[bi] for k, v in ridx.items()})
            if node._oc_fns:
                jenv = gather_env(idxmap, node)
                for f in node._oc_fns:
                    d, nl = f(jenv)
                    valid = valid & (d != 0) & ~nl
            return idxmap, valid

        idxmap, valid = eval_node(root)
        fenv = gather_env(idxmap, root)
        mask = valid
        for f in cond_fns:
            d, nl = f(fenv)
            mask = mask & (d != 0) & ~nl
        n_out = mask.shape[0]
        key_cols, key_nulls = [], []
        for f in key_fns:
            d, nl = dev.broadcast_1d(*f(fenv), n_out)
            key_cols.append(d.astype(jnp.int64))
            key_nulls.append(nl)
        if not key_cols:
            key_cols = [jnp.zeros(n_out, dtype=jnp.int64)]
            key_nulls = [jnp.zeros(n_out, dtype=bool)]
        val_cols, val_nulls = [], []
        for f, conv in val_plan:
            d, nl = dev.broadcast_1d(*f(fenv), n_out)
            if conv == "int":
                d = d.astype(jnp.int64)
            val_cols.append(d)
            val_nulls.append(nl)

        # stage 1: per-shard partial aggregation into bounded state
        pk, pkn, pres, presn, png, pvalid = dev._agg_impl(
            tuple(key_cols), tuple(key_nulls),
            tuple(val_cols), tuple(val_nulls), mask,
            n_keys=n_keys, agg_ops=agg_ops, capacity=capacity,
            pack=key_pack)

        # exchange: every shard's bounded partial state (capacity rows —
        # tiny next to N) rides ICI to every shard
        def g(x):
            return jax.lax.all_gather(x, AXIS, tiled=True)

        gk = tuple(g(k) for k in pk)
        gkn = tuple(g(k) for k in pkn)
        gres = tuple(g(r) for r in pres)
        gresn = tuple(g(r) for r in presn)
        gvalid = g(pvalid)

        # stage 2: replicated final merge — just another _agg_impl over
        # the gathered partials with partial→merge op mapping
        f_out = dev._agg_impl(gk, gkn, gres, gresn, gvalid,
                              n_keys=n_keys, agg_ops=merge_ops,
                              capacity=capacity, pack=key_pack)
        png_max = jax.lax.pmax(png, AXIS)
        # exact per-join required totals (pmax: worst shard governs the
        # static capacity); int64 — totals exceed int32 at TPC-H scale
        ovfs = tuple(jax.lax.pmax(o.astype(jnp.int64), AXIS)
                     for o in overflows)
        sovfs = tuple(jax.lax.pmax(o.astype(jnp.int32), AXIS)
                      for o in span_ovfs)
        xovfs_out = tuple(jax.lax.pmax(o.astype(jnp.int32), AXIS)
                          for o in xovfs)
        return f_out, png_max, ovfs, sovfs, xovfs_out

    n_res = len(val_plan)
    out_specs = (
        ((P(),) * n_keys, (P(),) * n_keys, (P(),) * n_res, (P(),) * n_res,
         P(), P()),
        P(),
        (P(),) * n_joins,
        (P(),) * n_joins,
        (P(),) * n_xovf,
    )
    wrapped = shard_map(
        body, mesh=mesh,
        in_specs=(env_specs, (P(AXIS),) * len(sharded_ids)),
        out_specs=out_specs, check_vma=False)

    def entry(env, svalids):
        # trace marker OUTSIDE the shard_map body (which tracing may
        # evaluate more than once): mpp fragment compiles meter into the
        # same pipe-cache stats as the single-chip pipelines
        dev._note_trace()
        return wrapped(env, svalids)

    return dev.observed_jit(entry)


# ---------------------------------------------------------------------------
# host entry points
# ---------------------------------------------------------------------------

def mpp_agg(plan, chunk, conds, ctx, mesh):
    """scan→filter→group-by fragment over the mesh (partition-parallel
    partial agg + collective merge — the shuffle-agg MPP fragment)."""
    if chunk.num_rows == 0:
        raise DeviceUnsupported("empty input")
    leaf = _Leaf(0, chunk, list(conds), 0)
    return _run_mpp(plan, [], leaf, [leaf], [], ctx, mesh)


def mpp_join_agg(agg_plan, agg_conds, child_exec, ctx, mesh):
    """join-tree→group-by fragment over the mesh: probe spine sharded,
    build sides broadcast (the broadcast hash join MPP variant)."""
    root, leaves, joins = collect_tree(child_exec)
    if any(jn.kind != "inner" for jn in joins):
        # the mesh fragment compiler shards/broadcasts inner joins only
        raise DeviceUnsupported("non-inner join in MPP fragment")
    from ..storage.paged import chunk_is_paged
    if any(chunk_is_paged(leaf.chunk) for leaf in leaves):
        # MPP shards whole resident columns across the mesh; a disk-backed
        # table must stream through the paged single-chip pipeline instead
        raise DeviceUnsupported("paged leaf in MPP fragment")
    return _run_mpp(agg_plan, agg_conds, root, leaves, joins, ctx, mesh)


def _build_key_leaf(node, leaves):
    """The leaf inside `node`'s build (right) subtree holding ALL of the
    right-key columns — the one a Hash exchange must repartition; None
    when the keys span leaves (or reference none)."""
    used = set()
    for k in node.right_keys:
        k.columns_used(used)
    if not used:
        return None
    gls = {node.right.offset + u for u in used}
    for leaf in leaves:
        if (leaf.offset >= node.right.offset
                and leaf.offset + leaf.ncols
                <= node.right.offset + node.right.ncols
                and all(leaf.offset <= g < leaf.offset + leaf.ncols
                        for g in gls)):
            return leaf
    return None


def _run_mpp(plan, agg_conds, root, leaves, joins, ctx, mesh):
    from ..utils import failpoint as _fp
    # chaos/supervisor hook: a `sleep(...)` here models a hung collective
    # at the MPP fragment boundary (the exchange-dispatch analog of
    # device-agg-exec / device-join-exec)
    _fp.inject("device-mpp-exec")
    n_shards = mesh.shape[AXIS]

    # The shard leaf must sit on the probe (left) spine: every join's
    # build side must be complete on every shard. Orient the tree so the
    # LARGEST table is that leaf — inner-join probe/build sides are a
    # physical choice (swapping is legal), and the global column offsets
    # are untouched (a node's column range spans both subtrees either
    # way). This also minimizes broadcast volume: big table sharded,
    # dimensions replicated.
    bottom = None
    if joins:
        target = max(leaves, key=lambda lf: lf.chunk.num_rows).leaf_id
        node = root
        prev = None
        while isinstance(node, _JoinNode):
            if target in node.right.leaf_ids:
                node.left, node.right = node.right, node.left
                node.left_keys, node.right_keys = (
                    node.right_keys, node.left_keys)
            prev = node
            node = node.left
        shard_leaf = node.leaf_id
        bottom = prev  # the spine join directly over the sharded leaf
    else:
        shard_leaf = root.leaf_id
    shard_rows = leaves[shard_leaf].chunk.num_rows
    if shard_rows < n_shards:
        raise DeviceUnsupported("too few rows to shard over the mesh")

    # broadcast-vs-shuffle for the bottom join (reference: the planner
    # picks Broadcast vs HashPartition exchange by build-side size,
    # exhaust_physical_plans.go MPP join variants): when the build-key
    # leaf is itself fact-sized, replicating it per shard would blow
    # HBM — hash-repartition it (and the probe fact) over the mesh
    # instead. The exchanged leaf is the one holding ALL the bottom
    # join's right-key columns; any other build-subtree leaves stay
    # replicated, so the subtree's local joins remain co-partitioned
    # by the exchanged key.
    shuffle_build = None
    if bottom is not None:
        bleaf = _build_key_leaf(bottom, leaves)
        if bleaf is not None:
            try:
                bc_rows = int(ctx.get_sysvar(
                    "tidb_broadcast_join_threshold_count"))
            except Exception:
                bc_rows = 10 * 1024
            build_rows = bleaf.chunk.num_rows
            if (bc_rows > 0 and build_rows > bc_rows
                    and build_rows >= n_shards):
                shuffle_build = bleaf.leaf_id
                # skew guard (SURVEY §7 "MPP shuffle skew"): a Hash
                # exchange sends every row of a key to ONE shard, so a
                # hot key turns balanced buckets into one overflowing
                # bucket — capacity doubles chase the hottest key while
                # the other shards idle. The host knows the hottest
                # key's row count from the build-side join index
                # (numpy, cached per table version); when it dwarfs the
                # uniform share, fall back to the Broadcast exchange
                # (reference: the planner picks Broadcast vs
                # HashPartition by cost, exhaust_physical_plans.go MPP
                # variants — skew is a cost input here)
                from .device_join import _leaf_index
                # right_keys are subtree-relative; rebase to bleaf-local
                local = [_shift_expr(k, bottom.right.offset - bleaf.offset)
                         for k in bottom.right_keys]
                bidx = _leaf_index(bleaf, local)
                if bidx is not None:
                    even_share = max(build_rows // n_shards, 1)
                    if bidx.max_cnt > 4 * even_share:
                        shuffle_build = None
                        MPP_STATS["skew_broadcasts"] = (
                            MPP_STATS.get("skew_broadcasts", 0) + 1)
    sharded_ids = [shard_leaf] + (
        [shuffle_build] if shuffle_build is not None else [])

    dcols = _global_dcols(leaves)
    key_fns, key_meta, key_pack, val_plan, agg_ops, slots = _plan_agg(
        plan, dcols)
    n_keys = max(len(key_fns), 1)
    if any(op not in _MERGE_OP for op in agg_ops):
        # cnt_dist partial states don't merge across shards (counts, not
        # sets) — single-chip kernel handles distinct
        raise DeviceUnsupported("non-mergeable agg on the mesh path")

    leaf_cond_fns = [
        [dev.compile_expr(_shift_expr(c, leaf.offset),
                          {leaf.offset + i: dc
                           for i, dc in _leaf_env(leaf).items()})
         for c in leaf.conds] for leaf in leaves]
    for jn in joins:
        jn._lk_fns = [dev.compile_expr(_shift_expr(k, jn.left.offset), dcols)
                      for k in jn.left_keys]
        jn._rk_fns = [dev.compile_expr(_shift_expr(k, jn.right.offset), dcols)
                      for k in jn.right_keys]
        jn._oc_fns = [dev.compile_expr(_shift_expr(c, jn.offset), dcols)
                      for c in jn.other_conds]
    cond_fns = [dev.compile_expr(c, dcols) for c in agg_conds]

    # mesh placement: sharded fact (and shuffled build) columns +
    # replicated dimensions
    env, env_specs = {}, {}
    for leaf in leaves:
        sharded = leaf.leaf_id in sharded_ids
        spec = (P(AXIS), P(AXIS)) if sharded else (P(), P())
        for i, dc in _leaf_env(leaf).items():
            env[leaf.offset + i] = _place_col(
                dc.data, dc.nulls, mesh, sharded, n_shards)
            env_specs[leaf.offset + i] = spec
    svalids = tuple(_valid_array(leaves[lid].chunk.num_rows, mesh, n_shards)
                    for lid in sharded_ids)

    # static capacities: per-shard probe rows bound the bottom join; each
    # join's output bounds the next (FK heuristic, doubled on overflow).
    # With shuffle, each exchanged side gets a per-destination bucket
    # capacity (~2x the uniform share), and the bottom join's probe side
    # becomes the post-exchange n_shards*cap_l rows.
    per_shard = -(-shard_rows // n_shards)
    xcaps = None
    if shuffle_build is not None:
        build_per_shard = -(-leaves[shuffle_build].chunk.num_rows // n_shards)
        xcaps = [dev.next_pow2(max(2 * (-(-per_shard // n_shards)), 8)),
                 dev.next_pow2(max(2 * (-(-build_per_shard // n_shards)), 8))]

    def leaf_rows(nd):
        if xcaps is not None and nd.leaf_id == shard_leaf:
            return n_shards * xcaps[0]
        return per_shard if nd.leaf_id == shard_leaf else nd.chunk.num_rows

    def est_rows(nd):
        # FK-join heuristic: output ≈ larger input, composed over the
        # subtree (see device_join.py est_rows) — starting from the probe
        # side alone needed a recompile per doubling to reach fact scale
        if isinstance(nd, _Leaf):
            return max(leaf_rows(nd), 8)
        return max(est_rows(nd.left), est_rows(nd.right))

    def init_caps():
        caps = []
        for jn in joins:
            jn.cap = dev.next_pow2(est_rows(jn))
            caps.append(jn.cap)
        return caps

    caps = init_caps()
    n_frag = caps[-1] if caps else per_shard
    est = _estimate_groups(plan, n_frag, ctx)
    capacity = dev.next_pow2(min(max(n_frag, 16), max(est, 16)))

    sig = ("mpp", n_shards, fragment_sig(leaves, joins, agg_conds, plan),
           tuple(sharded_ids))
    dict_refs = tuple(dc.dictionary for dc in dcols.values()
                      if dc.dictionary is not None)
    bottom_idx = joins.index(bottom) if bottom is not None else -1

    # retry discipline (reference: the Backoffer every coprocessor/MPP
    # dispatch carries, store/tikv/backoff.go): exchange transport faults
    # back off and retry on the SAME capacities; bucket/group overflow
    # "retries" are recompiles at larger capacity and draw from a separate
    # attempt budget.  Exhausting the transport budget surfaces a
    # classified BackoffExhaustedError (and trips the device breaker);
    # exhausting the growth budget degrades to the host engine.
    from ..utils import failpoint
    from ..utils.backoff import (Backoffer, ExchangeError)
    from ..utils.failpoint import FailpointError
    from ..errors import BackoffExhaustedError
    bo = Backoffer.for_session(ctx)
    while True:
        for jn, cap in zip(joins, caps):
            jn.cap = cap
        shuffle = None
        if shuffle_build is not None:
            shuffle = (bottom, shard_leaf, shuffle_build,
                       xcaps[0], xcaps[1])
        key = (sig, tuple(caps), tuple(xcaps or ()), capacity, key_pack,
               tuple(agg_ops))
        fn = _pipe_cache_get(key)
        if fn is None:
            fn = _build_mpp_pipeline(
                mesh, leaves, joins, root, sharded_ids, leaf_cond_fns,
                cond_fns, key_fns, n_keys, val_plan, tuple(agg_ops),
                capacity, key_pack, env_specs, shuffle=shuffle)
            _pipe_cache_put(key, fn, dict_refs)
        try:
            failpoint.inject("mpp-exchange-send")
            agg_out, png_d, ovfs_d, sovfs_d, xovfs_d = fn(env, svalids)
            from .device_exec import AggFetch
            f = AggFetch(agg_out, extras=(png_d, ovfs_d, sovfs_d, xovfs_d))
            failpoint.inject("mpp-exchange-recv")
        except (FailpointError, ExchangeError, ConnectionError,
                TimeoutError) as e:
            # narrow on purpose: FileNotFoundError-class OSErrors are
            # bugs, not transient exchange weather — they must surface
            exc = (e if isinstance(e, ExchangeError)
                   else ExchangeError(f"mpp exchange failed: {e}"))
            try:
                bo.backoff("exchangeRetry", exc)
            except BackoffExhaustedError:
                from .circuit import get_breaker
                # same SESSION owner token AND the same fragment shape
                # run_device's allow() used (join trees dispatch under
                # shape="join" — charging "agg" would open the healthy
                # agg breaker and orphan the join probe's verdict); the
                # session token stays valid even though a supervised
                # dispatch runs this on a worker thread
                get_breaker(ctx,
                            shape="join" if joins else "agg").record_failure(
                    exc, session=getattr(ctx, "conn_id", None))
                raise
            MPP_STATS["exchange_retries"] += 1
            continue
        png, ovfs, sovfs, xovfs = f.extras
        fng = f.ng
        if any(int(s) for s in sovfs):
            raise DeviceUnsupported(
                "multi-key join value ranges exceed int64 packing")
        retry = False
        for i, o in enumerate(xovfs):
            if int(o):
                xcaps[i] *= 2
                retry = True
        if retry:
            # the bottom join's probe side grew with the exchange bucket
            caps[bottom_idx] = max(
                caps[bottom_idx],
                dev.next_pow2(max(n_shards * xcaps[0], 8)))
        for i, o in enumerate(ovfs):
            if int(o) > caps[i]:
                # jump to the worst shard's exact requirement in one step
                caps[i] = dev.next_pow2(int(o))
                retry = True
        max_ng = max(int(png), int(fng))
        if max_ng > capacity:
            capacity = dev.next_pow2(max_ng)
            retry = True
        if not retry:
            break
        MPP_STATS["retries"] += 1
        try:
            bo.backoff("exchangeGrow")
        except BackoffExhaustedError as e:
            raise DeviceUnsupported(
                "mpp fragment capacities did not converge") from e
    ng = int(fng)
    if ng == 0 and not plan.group_exprs:
        raise DeviceUnsupported("empty global aggregate")
    MPP_STATS["fragments"] += 1
    if shuffle_build is not None:
        MPP_STATS["shuffle_joins"] += 1
    key_out, key_null_out, results, result_nulls = f.body()
    return _assemble_agg(plan, key_meta, slots, dcols,
                         (key_out, key_null_out, results, result_nulls), ng)
