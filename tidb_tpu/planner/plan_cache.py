"""Prepared-statement plan cache (reference: planner/core/cache.go CacheKey,
common_plans.go Execute.getPhysicalPlan + rebuildRange, and the cacheable
checker planner/core/cacheable_checker.go).

Design: parameters survive planning as leaf Constants tagged with
``param_idx`` (constant folding and compare-refinement keep the tag —
refinement records its conversion in ``param_conv`` so a cache hit can redo
it on the new value). On a hit the session rebinds those constants in place
and re-runs the two value-dependent physical stages — partition pruning and
access-path choice — on the cached plan; that is this engine's analog of the
reference's Execute.rebuildRange. Statements that bake values anywhere else
(subqueries, IN lists, LIKE patterns, LIMIT ?, variables, now()-family
functions, CTEs) are rejected up front by :func:`is_cacheable`, mirroring
the reference's conservative Cacheable() walk.

The cache itself is per-session (the reference's prepared-plan cache is
session-scoped too) and LRU-bounded by ``tidb_prepared_plan_cache_size``.
Schema, statistics and plan-binding changes invalidate entries through
version counters folded into the key, not by eager sweeping.
"""

from __future__ import annotations

import dataclasses
from collections import OrderedDict

from ..expression.core import Constant, Expression, ScalarFunc
from ..parser import ast
from .logical import (
    Aggregation, DataSource, Join, LogicalPlan, Projection, Selection, Sort,
    TopN, Window,
)

# Functions whose value is fixed at plan time (folded as constants) but
# varies per execution — a cached plan would freeze the first execution's
# value (reference: cacheable_checker.go + expression.unFoldableFunctions).
UNCACHEABLE_FUNCS = frozenset({
    "now", "current_timestamp", "sysdate", "curdate", "current_date",
    "curtime", "current_time", "utc_date", "utc_time", "utc_timestamp",
    "unix_timestamp", "rand", "uuid", "sleep", "user", "current_user",
    "session_user", "system_user", "database", "schema", "connection_id",
    "last_insert_id", "found_rows", "row_count", "version", "benchmark",
})


def _walk_ast(node):
    """Yield every dataclass AST node reachable from `node`."""
    stack = [node]
    while stack:
        n = stack.pop()
        if isinstance(n, (list, tuple)):
            stack.extend(n)
            continue
        if not isinstance(n, ast.Node):
            continue
        yield n
        if dataclasses.is_dataclass(n):
            for f in dataclasses.fields(n):
                stack.append(getattr(n, f.name))


def is_cacheable(stmt) -> bool:
    """Conservative statement-level check (reference: Cacheable(), planner/
    core/cacheable_checker.go): True only when every value the plan bakes in
    is either a true literal or a rebindable tagged param Constant."""
    if not isinstance(stmt, (ast.SelectStmt, ast.SetOprStmt)):
        return False
    for n in _walk_ast(stmt):
        if isinstance(n, (ast.SubqueryExpr, ast.ExistsExpr,
                          ast.CompareSubquery, ast.VariableExpr)):
            return False
        if isinstance(n, ast.SelectStmt) and n.with_ctes:
            return False
        if isinstance(n, ast.SelectStmt) and n.for_update:
            return False
        if isinstance(n, ast.TableName) and n.as_of is not None:
            # stale reads pin a session ts at PLAN time (set_stmt_as_of);
            # a cache hit would skip that and silently read live data
            return False
        if isinstance(n, ast.FuncCall) and n.name in UNCACHEABLE_FUNCS:
            return False
        if isinstance(n, ast.Limit):
            # LIMIT/OFFSET are eval'd to ints at build time (builder.py)
            for sub in _walk_ast([n.count, n.offset]):
                if isinstance(sub, ast.ParamMarker):
                    return False
        if isinstance(n, ast.InExpr):
            # the IN value set is materialized at build time (build_in_set)
            for sub in _walk_ast(n.items):
                if isinstance(sub, ast.ParamMarker):
                    return False
        if isinstance(n, ast.LikeExpr):
            # the regex is precompiled at build time when the pattern is
            # constant — a param pattern would freeze the first pattern
            for sub in _walk_ast(n.pattern):
                if isinstance(sub, ast.ParamMarker):
                    return False
    return True


def param_kinds(params) -> tuple:
    """Type-kind signature of the bound parameters: a param whose python
    type changes between EXECUTEs gets a fresh plan (the baked comparison
    coercions may differ), mirroring the reference's inclusion of param
    types in the cache key (cache.go NewPlanCacheKey)."""
    return tuple(type(p).__name__ for p in params)


# ---------------------------------------------------------------------------
# plan-side: find/rebind tagged param constants


def _iter_node_exprs(p: LogicalPlan):
    if isinstance(p, DataSource):
        return p.pushed_conds
    if isinstance(p, Selection):
        return p.conds
    if isinstance(p, Projection):
        return p.exprs
    if isinstance(p, Join):
        return (p.left_keys + p.right_keys + p.other_conds)
    if isinstance(p, Aggregation):
        out = list(p.group_exprs)
        for a in p.aggs:
            out.extend(a.args)
        return out
    if isinstance(p, (Sort, TopN)):
        return [e for e, _d in p.by]
    if isinstance(p, Window):
        out = list(p.partition_exprs) + [e for e, _d in p.order_by]
        for f in p.funcs:
            out.extend(f.args)
        return out
    return ()


def collect_param_consts(plan: LogicalPlan):
    """All param-tagged Constant leaves in the optimized plan, with their
    recorded refinement conversion. Returns [(const, idx, conv)]."""
    found = []
    seen = set()

    def visit_expr(e: Expression):
        if isinstance(e, Constant):
            if e.param_idx is not None and id(e) not in seen:
                seen.add(id(e))
                found.append((e, e.param_idx, e.param_conv))
            return
        if isinstance(e, ScalarFunc):
            for a in e.args:
                visit_expr(a)

    def visit_plan(p: LogicalPlan):
        for e in _iter_node_exprs(p):
            visit_expr(e)
        for c in p.children:
            visit_plan(c)

    visit_plan(plan)
    return found


def rebind_params(entry_consts, params) -> bool:
    """Rebind new parameter values into a cached plan's tagged constants.
    Returns False when a recorded refinement no longer applies (e.g. the
    new string doesn't parse as a date) — the caller then re-plans."""
    from ..expression.builder import _python_value_to_constant
    from ..sqltypes import parse_date_str, parse_datetime_str

    for const, idx, conv in entry_consts:
        if idx >= len(params):
            return False
        base = _python_value_to_constant(params[idx])
        v = base.value
        if conv is not None and v is not None:
            s = v.decode() if isinstance(v, bytes) else str(v)
            try:
                if conv == "date":
                    v = parse_date_str(s)
                elif conv == "datetime":
                    v = parse_datetime_str(s)
                elif conv == "float":
                    v = float(s)
            except Exception:
                return False
        elif conv is not None and v is None:
            pass  # NULL rebinds as NULL regardless of refinement
        const.value = v
    return True


def reprune(plan: LogicalPlan, ctx):
    """Re-run the value-dependent physical stages on a cached plan after
    rebinding (the reference's Execute.rebuildRange analog): reset and
    re-prune partitions, re-choose access paths. Both stages re-derive
    from pushed_conds, so they are idempotent across hits."""
    from .access import choose_access_paths
    from .optimizer import prune_partitions_rule

    def reset(p):
        if isinstance(p, DataSource) and p.table_info.partition is not None:
            p.partitions = list(p.table_info.partition.defs)
        for c in p.children:
            reset(c)

    reset(plan)
    prune_partitions_rule(plan)
    choose_access_paths(plan, ctx)


class SessionPlanCache:
    """LRU keyed by (digest, db, schema ver, stats ver, binding ver,
    param kinds) (reference: planner/core/cache.go NewPlanCacheKey)."""

    def __init__(self):
        self._lru = OrderedDict()
        self.hits = 0
        self.misses = 0

    def get(self, key):
        e = self._lru.get(key)
        if e is not None:
            self._lru.move_to_end(key)
            self.hits += 1
        else:
            self.misses += 1
        return e

    def put(self, key, plan, consts, capacity: int):
        if capacity <= 0:
            return
        self._lru[key] = (plan, consts)
        self._lru.move_to_end(key)
        while len(self._lru) > capacity:
            self._lru.popitem(last=False)

    def clear(self):
        self._lru.clear()
