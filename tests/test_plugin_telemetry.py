"""Plugin SPI (audit + authentication) and the local-only telemetry
collector (reference: plugin/spi.go, plugin/audit.go, telemetry/)."""

import json

import pytest

from tidb_tpu.plugin import (
    EVENT_STMT, KIND_AUDIT, KIND_AUTHENTICATION, Plugin,
)
from tidb_tpu.testkit import TestKit


@pytest.fixture()
def tk():
    tk = TestKit()
    tk.must_exec("use test")
    return tk


class _Recorder(Plugin):
    name = "recorder"
    kind = KIND_AUDIT

    def __init__(self):
        self.events = []
        self.inited = False

    def on_init(self, domain):
        self.inited = True

    def on_general_event(self, session, sql, event_class):
        self.events.append((event_class, sql))


class _Gate(Plugin):
    name = "gate"
    kind = KIND_AUTHENTICATION

    def __init__(self, allow):
        self.allow = allow

    def authenticate(self, user, host, auth_data):
        if user == "gated":
            return self.allow
        return None


class TestAuditPlugin:
    def test_general_events_fire_per_statement(self, tk):
        rec = _Recorder()
        tk.session.domain.plugins.load(rec)
        assert rec.inited
        tk.must_exec("create table t (a int)")
        tk.must_exec("insert into t values (1)")
        tk.must_query("select * from t")
        kinds = [e for e, _s in rec.events]
        assert kinds.count(EVENT_STMT) >= 3
        assert any("SELECT" in s.upper() for _e, s in rec.events)
        tk.session.domain.plugins.unload("recorder")
        n = len(rec.events)
        tk.must_exec("insert into t values (2)")
        assert len(rec.events) == n  # unloaded: no more events

    def test_failing_plugin_never_breaks_statements(self, tk):
        class Bomb(Plugin):
            name = "bomb"
            kind = KIND_AUDIT

            def on_general_event(self, session, sql, event_class):
                raise RuntimeError("boom")
        tk.session.domain.plugins.load(Bomb())
        tk.must_exec("create table t2 (a int)")  # must not raise
        assert any("boom" in e for e in tk.session.domain.plugins.errors)
        tk.session.domain.plugins.unload("bomb")

    def test_show_plugins(self, tk):
        tk.session.domain.plugins.load(_Recorder())
        rows = {tuple(r[:3]) for r in tk.must_query("show plugins").rows}
        assert ("recorder", "ACTIVE", "audit") in rows
        tk.session.domain.plugins.unload("recorder")

    def test_on_init_may_execute_sql(self, tk):
        """Regression: on_init runs outside the registry lock, so a plugin
        that bootstraps its own table must not deadlock."""
        domain = tk.session.domain

        class Boot(Plugin):
            name = "boot"
            kind = KIND_AUDIT

            def on_init(self, dom):
                from tidb_tpu.session import new_session
                s = new_session(dom)
                try:
                    s.execute("use test")
                    s.execute("create table if not exists audit_log (a int)")
                finally:
                    s.close()
        domain.plugins.load(Boot())
        tk.must_query("select count(*) from audit_log").check([("0",)])
        domain.plugins.unload("boot")

    def test_duplicate_load_rejected(self, tk):
        tk.session.domain.plugins.load(_Recorder())
        with pytest.raises(ValueError):
            tk.session.domain.plugins.load(_Recorder())
        tk.session.domain.plugins.unload("recorder")


class TestAuthPlugin:
    def test_plugin_decides_before_grant_tables(self, tk):
        reg = tk.session.domain.plugins
        reg.load(_Gate(allow=False))
        assert reg.authenticate("gated", "h", b"") is False
        assert reg.authenticate("other", "h", b"") is None  # falls through
        reg.unload("gate")
        reg.load(_Gate(allow=True))
        assert reg.authenticate("gated", "h", b"") is True
        reg.unload("gate")


class TestTelemetry:
    def test_disabled_by_default_no_report(self, tk):
        tel = tk.session.domain.telemetry
        assert tel.report_once() is None
        assert tel.history == []

    def test_enabled_collects_locally(self, tk):
        tk.must_exec("create table t (a int)")
        tk.must_exec("create view v as select a from t")
        tk.must_exec("set global tidb_enable_telemetry = ON")
        tel = tk.session.domain.telemetry
        payload = tel.report_once()
        assert payload is not None and len(tel.history) == 1
        fu = payload["featureUsage"]
        assert fu["tables"] >= 1 and fu["views"] >= 1
        tk.must_exec("set global tidb_enable_telemetry = OFF")

    def test_admin_show_telemetry(self, tk):
        rows = tk.must_query("admin show telemetry").rows
        assert rows[0][1] == "disabled"
        payload = json.loads(rows[0][2])
        assert "featureUsage" in payload and "cluster" in payload
