"""Host (numpy) operator kernels: factorize / group-agg / join / sort.

These are the CPU executor the TPU path is benchmarked against, and the
fallback for types the device cannot hold (arbitrary bytes). The algorithms
are deliberately the same shape as the device kernels (sort-based grouping,
sort + searchsorted joins) so host/device parity is structural.
"""

from __future__ import annotations

import zlib

import numpy as np


def factorize_column(data: np.ndarray, nulls: np.ndarray):
    """-> int64 codes with NULL = -1, plus unique count."""
    if data.dtype == object:
        # bytes keys: factorize via np.unique on object array
        uniques, inv = np.unique(data, return_inverse=True)
        codes = inv.astype(np.int64)
    else:
        uniques, inv = np.unique(data, return_inverse=True)
        codes = inv.astype(np.int64)
    codes = np.where(nulls, np.int64(-1), codes)
    return codes, len(uniques)


def combine_keys(columns):
    """columns: [(data, nulls)] -> single int64 key per row (collision-free
    via mixed-radix over factorized codes). NULLs are distinct group values
    (SQL GROUP BY treats NULLs as equal)."""
    if not columns:
        return np.zeros(0, dtype=np.int64)
    n = len(columns[0][0])
    acc = np.zeros(n, dtype=np.int64)
    for data, nulls in columns:
        codes, card = factorize_column(data, nulls)
        acc = acc * np.int64(card + 1) + (codes + 1)
    return acc


def group_ids(key_columns):
    """-> (gid per row int64, n_groups, first_row_index per group)."""
    combined = combine_keys(key_columns)
    uniques, first_idx, inv = np.unique(combined, return_index=True,
                                        return_inverse=True)
    return inv.astype(np.int64), len(uniques), first_idx


def seg_sum_int(gids, n_groups, values, nulls):
    """Per-group exact integer sums. Wide decimals (object arrays of
    Python ints) and int64 inputs whose total could overflow accumulate
    as arbitrary-precision Python ints (reference: types/mydecimal.go
    exact decimal arithmetic; SUM never silently wraps)."""
    if values.dtype == object:
        acc = np.zeros(n_groups, dtype=object)
        np.add.at(acc, gids, np.where(nulls, 0, values))
        return acc
    v = np.where(nulls, 0, values.astype(np.int64))
    # conservative wrap bound from exact min/max (np.abs would itself wrap
    # on INT64_MIN): n * max|v| must fit int64 or accumulate as bigints
    if len(v):
        max_abs = max(-int(v.min()), int(v.max()), 1)
        if len(v) * max_abs > (1 << 62):
            acc = np.zeros(n_groups, dtype=object)
            np.add.at(acc, gids, v.astype(object))
            return acc
    acc = np.zeros(n_groups, dtype=np.int64)
    np.add.at(acc, gids, v)
    return acc


def seg_sum_float(gids, n_groups, values, nulls):
    acc = np.zeros(n_groups, dtype=np.float64)
    v = np.where(nulls, 0.0, values.astype(np.float64))
    np.add.at(acc, gids, v)
    return acc


def seg_count(gids, n_groups, nulls=None):
    if nulls is None:
        return np.bincount(gids, minlength=n_groups).astype(np.int64)
    return np.bincount(gids[~nulls], minlength=n_groups).astype(np.int64)


def seg_min(gids, n_groups, values, nulls):
    if values.dtype == object:
        out = np.empty(n_groups, dtype=object)
        seen = np.zeros(n_groups, dtype=bool)
        for i in range(len(values)):
            if nulls[i]:
                continue
            g = gids[i]
            if not seen[g] or values[i] < out[g]:
                out[g] = values[i]
                seen[g] = True
        for g in range(n_groups):
            if not seen[g]:
                out[g] = b""
        return out, ~seen
    big = _max_sentinel(values.dtype)
    acc = np.full(n_groups, big, dtype=values.dtype)
    v = np.where(nulls, big, values)
    np.minimum.at(acc, gids, v)
    empty = acc == big
    return acc, empty


def seg_max(gids, n_groups, values, nulls):
    if values.dtype == object:
        out = np.empty(n_groups, dtype=object)
        seen = np.zeros(n_groups, dtype=bool)
        for i in range(len(values)):
            if nulls[i]:
                continue
            g = gids[i]
            if not seen[g] or values[i] > out[g]:
                out[g] = values[i]
                seen[g] = True
        for g in range(n_groups):
            if not seen[g]:
                out[g] = b""
        return out, ~seen
    small = _min_sentinel(values.dtype)
    acc = np.full(n_groups, small, dtype=values.dtype)
    v = np.where(nulls, small, values)
    np.maximum.at(acc, gids, v)
    empty = acc == small
    return acc, empty


def _max_sentinel(dt):
    if np.issubdtype(dt, np.floating):
        return np.inf
    return np.iinfo(dt).max


def _min_sentinel(dt):
    if np.issubdtype(dt, np.floating):
        return -np.inf
    return np.iinfo(dt).min


# ---------------------------------------------------------------------------
# joins (reference: executor/join.go hash join build/probe — here sort-based
# with identical output semantics)
# ---------------------------------------------------------------------------

def join_match(build_keys, probe_keys):
    """Equi-join matcher.

    build_keys / probe_keys: [(data, nulls)] parallel key column lists.
    Returns (probe_idx, build_idx): row-index pairs for every match.
    NULL keys never match (SQL equality).
    """
    nb = len(build_keys[0][0]) if build_keys else 0
    npr = len(probe_keys[0][0]) if probe_keys else 0
    if nb == 0 or npr == 0:
        return (np.zeros(0, dtype=np.int64), np.zeros(0, dtype=np.int64))
    if (len(build_keys) == 1
            and getattr(build_keys[0][0].dtype, "kind", "") in "iu"
            and getattr(probe_keys[0][0].dtype, "kind", "") in "iu"):
        # single integer key: raw values ARE a valid equality order — the
        # factorization pass (an O((nb+np)·log) sort over the concat of
        # BOTH sides) buys nothing; the merge matcher's sort + binary
        # search does the same job on raw values with correct NULL
        # handling. Measured: SF10 Q3's host hash join spent over half
        # its time in the concat np.unique.
        return merge_join_match(build_keys[0], probe_keys[0])
    # factorize over the concatenation so codes agree across sides
    b_null = np.zeros(nb, dtype=bool)
    p_null = np.zeros(npr, dtype=bool)
    acc_b = np.zeros(nb, dtype=np.int64)
    acc_p = np.zeros(npr, dtype=np.int64)
    for (bd, bn), (pd, pn) in zip(build_keys, probe_keys):
        both = np.concatenate([_norm(bd), _norm(pd)])
        codes, card = factorize_column(both, np.concatenate([bn, pn]))
        acc_b = acc_b * np.int64(card + 1) + (codes[:nb] + 1)
        acc_p = acc_p * np.int64(card + 1) + (codes[nb:] + 1)
        b_null |= bn
        p_null |= pn
    # sort build side, binary search probe rows
    order = np.argsort(acc_b, kind="stable")
    sorted_b = acc_b[order]
    lo = np.searchsorted(sorted_b, acc_p, side="left")
    hi = np.searchsorted(sorted_b, acc_p, side="right")
    cnt = hi - lo
    cnt = np.where(p_null, 0, cnt)
    total = int(cnt.sum())
    probe_idx = np.repeat(np.arange(npr, dtype=np.int64), cnt)
    # offsets within each probe row's match range
    starts = np.repeat(lo, cnt)
    cum = np.concatenate([[0], np.cumsum(cnt)[:-1]])
    within = np.arange(total, dtype=np.int64) - np.repeat(cum, cnt)
    build_idx = order[starts + within]
    # drop null-key build rows (they were factorized as -1+1=0 codes which
    # can't collide with real codes because codes start at 1)
    keep = ~b_null[build_idx]
    return probe_idx[keep], build_idx[keep]


def _norm(data):
    return data


def _mix64_np(u):
    """murmur3 fmix64 over a uint64 array (silent C wraparound)."""
    u = u ^ (u >> np.uint64(33))
    u = u * np.uint64(0xFF51AFD7ED558CCD)
    u = u ^ (u >> np.uint64(33))
    u = u * np.uint64(0xC4CEB9FE1A85EC53)
    u = u ^ (u >> np.uint64(33))
    return u


def _stable_obj_hash(x):
    """Process-stable hash for object keys (str/bytes). Python's hash() is
    randomized per process (PYTHONHASHSEED), which made spill partition
    layout — and therefore whether a pass fit its quota — nondeterministic
    across runs. crc32 is stable, C-speed, and feeds a 64-bit mixer."""
    if isinstance(x, str):
        x = x.encode("utf-8", "surrogatepass")
    elif isinstance(x, bytearray):
        x = bytes(x)
    return zlib.crc32(x)


def partition_ids(key_cols, n_parts):
    """Deterministic hash-partition id per row over [(data, nulls)] key
    columns (reference: the spill paths hash-partition build/probe/agg
    state, executor/aggregate.go + join spill). Equal keys — including
    across join sides after coercion — get equal ids; NULL key columns
    hash as one value, so the SQL NULL group stays in one partition."""
    n = len(key_cols[0][0])
    h = np.zeros(n, dtype=np.uint64)
    for d, nl in key_cols:
        if d.dtype == object:
            probe = next((x for x in d
                          if not isinstance(x, (bytes, bytearray, str))),
                         None)
            if isinstance(probe, int):
                # wide-decimal bigints: two's-complement low 64 bits, so a
                # value in int64 range hashes identically to the int64
                # representation on the other join side
                mask = (1 << 64) - 1
                hv = np.fromiter((x & mask for x in d), dtype=np.uint64,
                                 count=n)
            else:
                hv = np.fromiter((_stable_obj_hash(x) for x in d),
                                 dtype=np.int64, count=n).view(np.uint64)
        elif d.dtype.kind == "f":
            dd = np.where(d == 0, 0.0, d).astype(np.float64)  # -0.0 == 0.0
            hv = dd.view(np.uint64)
        else:
            hv = d.astype(np.int64).view(np.uint64)
        hv = np.where(nl, np.uint64(0), hv)
        h = _mix64_np(h ^ _mix64_np(hv))
    return (h % np.uint64(n_parts)).astype(np.int64)


def merge_join_match(build_key, probe_key):
    """Single primitive-key equi-join by direct sort + binary search
    (reference: executor/merge_join.go — the sort-order-exploiting
    alternative; here the order is produced in-kernel, skipping
    join_match's factorization pass over the concatenated sides).

    build_key / probe_key: (data, nulls). Returns (probe_idx, build_idx).
    """
    (bd, bn), (pd, pn) = build_key, probe_key
    nb, npr = len(bd), len(pd)
    if nb == 0 or npr == 0:
        return (np.zeros(0, dtype=np.int64), np.zeros(0, dtype=np.int64))
    if bd.dtype != pd.dtype:
        common = np.result_type(bd.dtype, pd.dtype)
        bd = bd.astype(common)
        pd = pd.astype(common)
    order = np.argsort(bd, kind="stable")
    sorted_b = bd[order]
    lo = np.searchsorted(sorted_b, pd, side="left")
    hi = np.searchsorted(sorted_b, pd, side="right")
    cnt = np.where(pn, 0, hi - lo)
    total = int(cnt.sum())
    probe_idx = np.repeat(np.arange(npr, dtype=np.int64), cnt)
    starts = np.repeat(lo, cnt)
    cum = np.concatenate([[0], np.cumsum(cnt)[:-1]])
    within = np.arange(total, dtype=np.int64) - np.repeat(cum, cnt)
    build_idx = order[starts + within]
    keep = ~bn[build_idx]
    return probe_idx[keep], build_idx[keep]


def semi_mask(build_keys, probe_keys):
    """-> bool mask over probe rows: has >=1 match."""
    pi, _bi = join_match(build_keys, probe_keys)
    npr = len(probe_keys[0][0])
    mask = np.zeros(npr, dtype=bool)
    mask[pi] = True
    return mask


# ---------------------------------------------------------------------------
# sort / topn
# ---------------------------------------------------------------------------

def sort_indices(key_columns, descs, nulls_first=True):
    """key_columns: [(data, nulls)] in major-to-minor order; descs: [bool].
    MySQL: NULLs sort first ASC, last DESC. -> permutation indices."""
    n = len(key_columns[0][0])
    keys = []
    # np.lexsort takes minor-to-major
    for (data, nulls), desc in zip(reversed(key_columns), reversed(descs)):
        if data.dtype == object:
            # factorize preserves order for bytes
            uniq, inv = np.unique(data, return_inverse=True)
            d = inv.astype(np.int64)
        else:
            d = data
        if desc:
            if np.issubdtype(np.asarray(d).dtype, np.floating):
                d = -d.astype(np.float64)
            else:
                d = -d.astype(np.int64)
        keys.append(np.where(nulls, 0, d))
        # null rank key: ASC -> nulls first (0), non-null 1; DESC -> nulls last
        null_rank = np.where(nulls, 0 if not desc else 1, 1 if not desc else 0)
        keys.append(null_rank)
    return np.lexsort(keys)
