"""Functional-dependency group-key pruning (reference: planner/funcdep/
fd_graph.go feeding rule_aggregation_elimination.go): GROUP BY keys that
the remaining keys determine — via a unique key of a joined base table
plus the inner-join equality closure — demote to first_row() aggregates.
The Q3/Q18 shapes shrink to a single group key, which keeps the device
aggregation inside its packed dense-scatter span."""

import numpy as np
import pytest

from tidb_tpu.testkit import TestKit


@pytest.fixture(scope="module")
def tk():
    tk = TestKit()
    tk.must_exec("use test")
    tk.must_exec(
        "create table fo (o_ok bigint primary key, o_ck bigint,"
        " o_date date, o_prio bigint)")
    tk.must_exec(
        "create table fl (l_ok bigint, l_price decimal(15,2),"
        " l_qty bigint)")
    tk.must_exec(
        "create table fc (c_ck bigint primary key, c_name varchar(20),"
        " c_seg varchar(10))")
    # no unique key at all on this one
    tk.must_exec("create table fn (n_id bigint, n_name varchar(20))")
    # nullable unique: must NOT count as a determining key
    tk.must_exec(
        "create table fu (u_id bigint, u_tag bigint,"
        " unique key uk (u_tag))")
    rng = np.random.default_rng(3)
    rows_o, rows_l, rows_c = [], [], []
    for i in range(1, 101):
        rows_o.append(
            f"({i}, {i % 17 + 1}, '199{i % 5}-0{i % 9 + 1}-11', {i % 3})")
    for i in range(1, 601):
        ok = int(rng.integers(1, 101))
        rows_l.append(f"({ok}, {int(rng.integers(100, 9999))}.25,"
                      f" {int(rng.integers(1, 50))})")
    for i in range(1, 18):
        rows_c.append(f"({i}, 'Cust#{i:05d}', 'SEG{i % 4}')")
    tk.must_exec("insert into fo values " + ",".join(rows_o))
    tk.must_exec("insert into fl values " + ",".join(rows_l))
    tk.must_exec("insert into fc values " + ",".join(rows_c))
    tk.must_exec("insert into fn values (1,'a'),(1,'a'),(2,'b')")
    tk.must_exec("insert into fu values (1, 10),(2, 20),(3, null),(4, null)")
    return tk


def _agg_line(tk, sql):
    for name, info in tk.must_query("explain " + sql).rows:
        if "HashAgg" in name or "StreamAgg" in name:
            return info
    return ""


Q3ISH = ("select l_ok, sum(l_price) rev, o_date, o_prio "
         "from fo, fl where l_ok = o_ok "
         "group by l_ok, o_date, o_prio")


def test_q3_shape_prunes_to_one_key(tk):
    info = _agg_line(tk, Q3ISH)
    assert "first_row" in info, info
    # one group key: the orders PK through the join equivalence
    assert info.count("group by:[") == 1
    head = info.split("funcs:")[0]
    assert "o_date" not in head and "o_prio" not in head, info


def test_q3_shape_results_match_unpruned(tk):
    got = sorted(tk.must_query(Q3ISH).rows)
    # force the unpruned semantics through a no-FD rewrite: group on the
    # lineitem side only (fl has no unique key, so nothing prunes) and
    # carry the orders columns through min() — equal because o_ok is
    # actually unique in the data
    ref = sorted(tk.must_query(
        "select l_ok, sum(l_price) rev, min(o_date), min(o_prio) "
        "from fo, fl where l_ok = o_ok group by l_ok").rows)
    assert got == ref


def test_five_key_q18_shape_prunes_to_pk(tk):
    sql = ("select c_name, c_ck, o_ok, o_date, sum(l_qty) "
           "from fc, fo, fl "
           "where c_ck = o_ck and o_ok = l_ok "
           "group by c_name, c_ck, o_ok, o_date")
    info = _agg_line(tk, sql)
    # o_ok determines o_* (PK), o_ck == c_ck via the join (PK of fc) →
    # c_name; a single key remains
    assert info.count("first_row") == 3, info
    got = sorted(tk.must_query(sql).rows)
    ref = sorted(tk.must_query(
        "select min(c_name), min(c_ck), o_ok, min(o_date), sum(l_qty) "
        "from fc, fo, fl where c_ck = o_ck and o_ok = l_ok "
        "group by o_ok").rows)
    assert got == ref


def test_no_unique_key_no_pruning(tk):
    info = _agg_line(
        tk, "select n_id, n_name, count(1) from fn group by n_id, n_name")
    assert "first_row" not in info, info


def test_nullable_unique_not_determining(tk):
    # u_tag is unique but nullable: two NULL-tag rows with different u_id
    # must stay separate groups, so u_id cannot demote
    sql = "select u_tag, u_id, count(1) from fu group by u_tag, u_id"
    info = _agg_line(tk, sql)
    assert "first_row" not in info, info
    rows = tk.must_query(sql).rows
    assert len(rows) == 4


def test_left_join_condition_adds_no_equivalence(tk):
    # LEFT JOIN: l_ok = o_ok fails to hold on null-extended rows, so l_ok
    # must NOT demote through the orders PK; but o_date (right side,
    # PK-determined on its own table) still may when o_ok is kept
    sql = ("select l_ok, o_ok, o_date, count(1) from fl "
           "left join fo on l_ok = o_ok and o_prio = 99 "
           "group by l_ok, o_ok, o_date")
    info = _agg_line(tk, sql)
    head = info.split("funcs:")[0]
    assert "l_ok" in head and "o_ok" in head, info
    assert "o_date" not in head, info
    # parity against the three-key grouping without pruning surface:
    # o_prio = 99 matches nothing, so every row is null-extended
    rows = tk.must_query(sql).rows
    ref = tk.must_query(
        "select l_ok, count(1) from fl group by l_ok").rows
    assert sorted((r[0], r[3]) for r in rows) == sorted(ref)
    assert all(r[1] is None and r[2] is None for r in rows)


def test_expression_key_demotes(tk):
    # year(o_date) is determined by o_ok even though it's an expression
    sql = ("select l_ok, year(o_date), sum(l_qty) from fo, fl "
           "where l_ok = o_ok group by l_ok, year(o_date)")
    info = _agg_line(tk, sql)
    assert "first_row" in info, info
    got = sorted(tk.must_query(sql).rows)
    ref = sorted(tk.must_query(
        "select l_ok, min(year(o_date)), sum(l_qty) from fo, fl "
        "where l_ok = o_ok group by l_ok").rows)
    assert got == ref


def test_nondeterministic_key_never_demotes(tk):
    # rand() is a fresh value per row: no FD determines it, and a
    # column-free expression must not be vacuously "determined"
    sql = "select o_date, count(1) from fo group by o_date, rand()"
    info = _agg_line(tk, sql)
    assert "first_row" not in info, info
    assert len(tk.must_query(sql).rows) == 100
    # deterministic expression over a determined column still may demote
    sql2 = ("select o_ok, o_prio + 1, count(1) from fo, fl "
            "where l_ok = o_ok group by o_ok, o_prio + 1")
    info2 = _agg_line(tk, sql2)
    assert "first_row" in info2, info2
    # ...but rand()-tainted expressions never do, even over determined
    # columns
    sql3 = ("select o_ok, count(1) from fo, fl where l_ok = o_ok "
            "group by o_ok, o_prio + rand()")
    info3 = _agg_line(tk, sql3)
    assert "first_row" not in info3, info3


def test_having_and_order_by_still_work(tk):
    sql = ("select l_ok, o_date, sum(l_qty) s from fo, fl "
           "where l_ok = o_ok group by l_ok, o_date "
           "having sum(l_qty) > 100 order by s desc, l_ok limit 5")
    rows = tk.must_query(sql).rows
    ref = tk.must_query(
        "select l_ok, min(o_date), sum(l_qty) s from fo, fl "
        "where l_ok = o_ok group by l_ok "
        "having sum(l_qty) > 100 order by s desc, l_ok limit 5").rows
    assert rows == ref
