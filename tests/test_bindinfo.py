"""Plan bindings (reference: bindinfo/handle.go, planner/optimize.go:147-207
binding match, mysql.bind_info)."""

import pytest

from tidb_tpu.testkit import TestKit


@pytest.fixture()
def tk():
    tk = TestKit()
    tk.must_exec("use test")
    tk.must_exec("create table t (id int primary key, a int, b int, key ia (a))")
    tk.must_exec("insert into t values "
                 + ",".join(f"({i},{i % 50},{i % 7})" for i in range(500)))
    tk.must_exec("analyze table t")
    return tk


def _explain(tk, sql):
    return "\n".join(" ".join(str(c) for c in r)
                     for r in tk.must_query("EXPLAIN " + sql).rows)


class TestIndexHints:
    def test_force_index(self, tk):
        txt = _explain(tk, "select * from t force index (ia) where a = 3")
        assert "index:ia" in txt

    def test_ignore_index(self, tk):
        txt = _explain(tk, "select * from t ignore index (ia) where a = 3")
        assert "IndexLookUp" not in txt and "TableScan" in txt

    def test_use_index_restricts_candidates(self, tk):
        tk.must_exec("alter table t add index ib (b)")
        txt = _explain(tk, "select * from t use index (ib) where a = 3")
        assert "index:ia" not in txt

    def test_hint_survives_restore(self, tk):
        from tidb_tpu.parser import parse
        s = parse("select * from t force index (ia) where a = 3")[0]
        assert "FORCE INDEX (`ia`)" in s.restore()


class TestSessionBindings:
    def test_binding_changes_plan_and_drops(self, tk):
        tk.must_exec("create session binding for "
                     "select * from t where a = 3 using "
                     "select * from t ignore index (ia) where a = 3")
        # literals normalize away: different constant still matches
        assert "IndexLookUp" not in _explain(tk, "select * from t where a = 77")
        rows = tk.must_query("show bindings").rows
        assert len(rows) == 1 and "IGNORE INDEX" in str(rows[0][1])
        tk.must_exec("drop session binding for select * from t where a = 3")
        assert "IndexLookUp" in _explain(tk, "select * from t where a = 3")

    def test_session_binding_is_session_local(self, tk):
        tk.must_exec("create session binding for "
                     "select * from t where a = 3 using "
                     "select * from t ignore index (ia) where a = 3")
        tk2 = tk.new_session()
        tk2.must_exec("use test")
        assert "IndexLookUp" in _explain(tk2, "select * from t where a = 3")


class TestGlobalBindings:
    def test_global_binding_applies_across_sessions(self, tk):
        tk.must_exec("create global binding for "
                     "select * from t where a = 3 using "
                     "select * from t ignore index (ia) where a = 3")
        tk2 = tk.new_session()
        tk2.must_exec("use test")
        assert "IndexLookUp" not in _explain(tk2, "select * from t where a = 9")
        assert len(tk.must_query("show global bindings").rows) == 1
        tk.must_exec("drop global binding for select * from t where a = 3")
        assert "IndexLookUp" in _explain(tk2, "select * from t where a = 3")

    def test_global_binding_persists_in_catalog(self, tk):
        """A new BindHandle over the same store sees the binding (the
        mysql.bind_info persistence role)."""
        from tidb_tpu.bindinfo import BindHandle
        tk.must_exec("create global binding for "
                     "select * from t where a = 3 using "
                     "select * from t force index (ia) where a = 3")
        fresh = BindHandle(tk.session.domain)
        assert len(fresh.list()) == 1
        tk.must_exec("drop global binding for select * from t where a = 3")

    def test_session_binding_shadows_global(self, tk):
        tk.must_exec("create global binding for "
                     "select * from t where a = 3 using "
                     "select * from t force index (ia) where a = 3")
        tk.must_exec("create session binding for "
                     "select * from t where a = 3 using "
                     "select * from t ignore index (ia) where a = 3")
        assert "IndexLookUp" not in _explain(tk, "select * from t where a = 3")
        tk.must_exec("drop session binding for select * from t where a = 3")
        tk.must_exec("drop global binding for select * from t where a = 3")


class TestBindingValidation:
    def test_binding_without_hints_rejected(self, tk):
        e = tk.exec_error("create session binding for "
                          "select * from t where a = 3 using "
                          "select * from t where a = 3")
        assert "no index hints" in str(e)

    def test_mismatched_statements_rejected(self, tk):
        tk.must_exec("create table x (b int, key ib (b))")
        e = tk.exec_error("create session binding for "
                          "select * from t where a = 3 using "
                          "select * from x use index (ib) where b = 2")
        assert "different" in str(e)

    def test_binding_scoped_to_database(self, tk):
        """A binding created in one db must not hijack a same-named table
        in another db."""
        tk.must_exec("create global binding for "
                     "select * from t where a = 3 using "
                     "select * from t ignore index (ia) where a = 3")
        tk.must_exec("create database otherdb")
        tk.must_exec("use otherdb")
        tk.must_exec("create table t (id int primary key, a int, key ia (a))")
        tk.must_exec("insert into t values "
                     + ",".join(f"({i},{i % 20})" for i in range(300)))
        tk.must_exec("analyze table t")
        assert "IndexLookUp" in _explain(tk, "select * from t where a = 3")
        tk.must_exec("use test")
        tk.must_exec("drop global binding for select * from t where a = 3")

    def test_prepared_stmt_unaffected_after_drop(self, tk):
        """Regression: binding hints must not persist on a cached prepared
        AST after DROP BINDING."""
        sess = tk.session
        stmt_ast, _np = sess.prepare("select * from t where a = 3")
        tk.must_exec("create session binding for "
                     "select * from t where a = 3 using "
                     "select * from t ignore index (ia) where a = 3")
        sess.execute_prepared(stmt_ast, [])
        tk.must_exec("drop session binding for select * from t where a = 3")
        # re-plan of the SAME ast must use the index again
        plan = sess.plan_query(stmt_ast)
        from tidb_tpu.planner.logical import explain_tree
        txt = "\n".join(f"{a} {b}" for a, b in explain_tree(plan))
        assert "IndexLookUp" in txt


class TestBindingSelfJoin:
    def test_per_occurrence_hints(self, tk):
        """A self-join binding keeps different hints per occurrence."""
        tk.must_exec("create session binding for "
                     "select * from t a, t b where a.id = b.id and a.a = 1 "
                     "using "
                     "select * from t a force index (ia), "
                     "t b ignore index (ia) "
                     "where a.id = b.id and a.a = 1")
        from tidb_tpu.bindinfo import hints_from_record
        rec = next(iter(tk.session.session_bindings.values()))
        verbs = [h[0][0] for _t, h in hints_from_record(rec) if h]
        assert sorted(verbs) == ["force", "ignore"]  # both occurrences kept
        # functional check: a (which carries the sargable filter) goes
        # through ia; b stays a plain scan
        txt = _explain(tk, "select * from t a, t b "
                           "where a.id = b.id and a.a = 5")
        assert txt.count("index:ia") == 1 and "table:a, index:ia" in txt
        tk.must_exec("drop session binding for "
                     "select * from t a, t b where a.id = b.id and a.a = 1")


class TestBindingPrivileges:
    def test_global_binding_requires_super(self, tk):
        tk.must_exec("create user 'plain'@'%'")
        tk.must_exec("grant select on test.* to 'plain'@'%'")
        tk2 = tk.new_session()
        tk2.session.user = "plain@%"
        e = tk2.exec_error("create global binding for "
                           "select * from t where a = 3 using "
                           "select * from t ignore index (ia) where a = 3")
        assert "denied" in str(e).lower()
        # session-scope bindings are allowed for any user
        tk2.must_exec("create session binding for "
                      "select * from t where a = 3 using "
                      "select * from t ignore index (ia) where a = 3")
