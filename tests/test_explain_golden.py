"""EXPLAIN plan regression tests — the cmd/explaintest analog (reference:
cmd/explaintest/t/*.test + r/*.result golden files, run-tests.sh runner).

Golden plans live in tests/golden_plans/<name>.result as the exact
EXPLAIN output. Regenerate after an intended planner change with:

    GOLDEN_RECORD=1 python -m pytest tests/test_explain_golden.py

(the reference regenerates with `-record` through testdata.LoadTestCases).
A diff here means the optimizer changed a plan — deliberate changes
update the golden file in the same commit, accidental ones are caught.
"""

import os
import pathlib

import pytest

from test_tpch import make_tpch_tk

GOLDEN_DIR = pathlib.Path(__file__).parent / "golden_plans"
RECORD = os.environ.get("GOLDEN_RECORD") == "1"


@pytest.fixture(scope="module")
def tk():
    t = make_tpch_tk(db="tpch_golden")
    for tbl in ("lineitem", "orders", "customer", "supplier", "part",
                "partsupp", "nation", "region"):
        t.must_exec(f"analyze table {tbl}")
    return t


CASES = {
    "q3": """
        select l_orderkey, sum(l_extendedprice * (1 - l_discount)) as rev,
               o_orderdate, o_shippriority
        from customer, orders, lineitem
        where c_mktsegment = 'BUILDING' and c_custkey = o_custkey
          and l_orderkey = o_orderkey and o_orderdate < '1995-03-15'
          and l_shipdate > '1995-03-15'
        group by l_orderkey, o_orderdate, o_shippriority
        order by rev desc, o_orderdate limit 10""",
    "q5": """
        select n_name, sum(l_extendedprice * (1 - l_discount)) as revenue
        from customer, orders, lineitem, supplier, nation, region
        where c_custkey = o_custkey and l_orderkey = o_orderkey
          and l_suppkey = s_suppkey and c_nationkey = s_nationkey
          and s_nationkey = n_nationkey and n_regionkey = r_regionkey
          and r_name = 'ASIA'
        group by n_name order by revenue desc""",
    "q9_shape": """
        select n_name, sum(l_extendedprice * (1 - l_discount)) as profit
        from part, supplier, lineitem, partsupp, nation
        where s_suppkey = l_suppkey and ps_suppkey = l_suppkey
          and ps_partkey = l_partkey and p_partkey = l_partkey
          and s_nationkey = n_nationkey and p_name like '%green%'
        group by n_name order by n_name""",
    "point_get": "select * from region where r_regionkey = 2",
    "index_range": """
        select o_orderkey from orders
        where o_custkey = 7 and o_orderdate > '1995-01-01'""",
    "outer_join_eliminated": """
        select o_orderkey, o_totalprice from orders
        left join customer on o_custkey = c_custkey""",
    "outer_join_kept": """
        select o_orderkey, c_name from orders
        left join customer on o_custkey = c_custkey""",
    "max_min_topn": "select max(o_totalprice) from orders",
    "hint_merge_join": """
        select /*+ MERGE_JOIN(orders) */ count(1)
        from customer, orders where c_custkey = o_custkey""",
    "hint_stream_agg": """
        select /*+ STREAM_AGG() */ o_custkey, count(1)
        from orders group by o_custkey""",
    "topn_pushdown_agg": """
        select l_orderkey, sum(l_quantity) q from lineitem
        group by l_orderkey order by q desc limit 5""",
}


def _plan_text(tk, sql):
    rows = tk.must_query("explain " + " ".join(sql.split())).rows
    return "\n".join(f"{name} | {info}" for name, info in rows)


@pytest.mark.parametrize("name", sorted(CASES))
def test_plan_golden(tk, name):
    got = _plan_text(tk, CASES[name])
    path = GOLDEN_DIR / f"{name}.result"
    if RECORD or not path.exists():
        GOLDEN_DIR.mkdir(exist_ok=True)
        path.write_text(got + "\n")
        if not RECORD:
            pytest.skip(f"golden recorded: {path.name}")
        return
    want = path.read_text().rstrip("\n")
    assert got == want, (
        f"plan changed for {name!r}:\n--- golden\n{want}\n--- got\n{got}\n"
        f"(GOLDEN_RECORD=1 regenerates if intended)")
