"""Unified retry/backoff budgets (reference: store/tikv/backoff.go).

The reference routes EVERY retriable distributed call through one
``Backoffer``: each retry *kind* (boTxnLock, boRegionMiss, ...) has a
capped exponential sleep curve with jitter, and the backoffer as a whole
carries a per-request sleep budget (``maxSleep`` scaled by
``tidb_backoff_weight``).  Exhausting the budget surfaces a *classified*
error that names every error the retries saw — never an unbounded loop.

This module is the in-process translation: the five ad-hoc retry loops
that grew in kv/store.py, session.py, ddl_worker.py and mpp_exec.py all
route through one Backoffer so a query's total retry budget is a single
number, KILL/max_execution_time can interrupt a sleeping retry, and
exhaustion is always a classified error.

Error taxonomy (classify()): the classes the distributed path can see —

    region     lock waits, write conflicts (the reference's region/lock
               errors: another writer owns the range right now)
    lease      leader-election or lease loss (coordinator campaigns)
    exchange   MPP exchange send/recv failure or shuffle overflow
    device     accelerator compile/OOM/runtime failure
    transport  remote-compile / tunnel transport errors (the dead-tunnel
               "Connection refused" mode from BENCH_TPU_LIVE.json)
    compile    the compile service could not BUILD a device executable
               (executor/compile_service.py — a remote-compile RPC died
               mid-build or an injected compile fault fired; distinct
               from `device`, which is an executable that RAN and failed)
    hang       a supervised device call blew its wall-clock deadline
               (executor/supervisor.py — the backend froze inside a
               GIL-holding C call, distinct from a device that ERRORS)
    admission  the serving scheduler refused the fragment a device slot
               (executor/scheduler.py — load pressure, not ill-health:
               the fragment degrades to the host engine)
    fault      an injected failpoint fired
    other      anything unclassified
"""

from __future__ import annotations

import logging
import random
import threading
import time

from ..errors import (BackoffExhaustedError, DeadlockError, LockedError,
                      SchemaChangedError, TiDBError, WriteConflictError)

log = logging.getLogger("tidb_tpu.backoff")

# -- error taxonomy ---------------------------------------------------------

CLASS_REGION = "region"
CLASS_LEASE = "lease"
CLASS_EXCHANGE = "exchange"
CLASS_DEVICE = "device"
CLASS_TRANSPORT = "transport"
CLASS_COMPILE = "compile"
CLASS_HANG = "hang"
CLASS_ADMISSION = "admission"
CLASS_FAULT = "fault"
CLASS_OTHER = "other"


#: message substrings (lowercased match) that mark a device runtime error
#: as OUT-OF-MEMORY — the jaxlib/XLA phrasings seen across backends:
#: "RESOURCE_EXHAUSTED: Out of memory allocating 12345 bytes", PJRT's
#: "Resource exhausted: Failed to allocate request for ...", the TPU
#: runtime's "Attempting to allocate ... exceeds ... memory available",
#: plus the allocator's generic failure lines. One table so the
#: classifier, the OOM-recovery ladder (executor/device_exec.run_device)
#: and the taxonomy unit test all agree.
DEVICE_OOM_MARKERS = (
    "resource_exhausted",
    "resource exhausted",
    "out of memory",
    "out_of_memory",
    "failed to allocate",
    "allocation failure",
    "exceeds the amount of memory available",
)

#: exception TYPE NAMES (matched anywhere in the MRO — jaxlib moves and
#: subclasses its runtime error across versions) that mark a device
#: runtime failure
DEVICE_ERROR_TYPE_NAMES = ("XlaRuntimeError", "JaxRuntimeError")


def _mro_names(err) -> set:
    return {c.__name__ for c in type(err).__mro__}


def classify(err) -> str:
    """Map an exception to its resilience class (one label the breaker,
    the backoffer and the slow log all agree on)."""
    from .failpoint import (FailpointError, InjectedCompileError,
                            InjectedSpillError)
    from ..errors import (DeviceAdmissionError, DeviceCompileError,
                          DeviceHangError)
    if isinstance(err, DeviceHangError):
        return CLASS_HANG
    if isinstance(err, DeviceAdmissionError):
        return CLASS_ADMISSION
    if isinstance(err, (DeviceCompileError, InjectedCompileError)):
        return CLASS_COMPILE
    if isinstance(err, (LockedError, WriteConflictError, DeadlockError,
                        SchemaChangedError)):
        return CLASS_REGION
    if isinstance(err, ExchangeError):
        return CLASS_EXCHANGE
    if isinstance(err, LeaseExpiredError):
        return CLASS_LEASE
    if isinstance(err, (FailpointError, InjectedSpillError)):
        # a spill-write failure mid-hybrid-join degrades to host like any
        # other injected fault (breaker-charged, spill pages drained)
        return CLASS_FAULT
    # deliberately NOT all of OSError: FileNotFoundError/PermissionError
    # and friends are programming/environment bugs that must surface, not
    # be retried or fed to the breaker as device-health signals
    if isinstance(err, (ConnectionError, BrokenPipeError, TimeoutError)):
        return CLASS_TRANSPORT
    msg = str(err)
    low = msg.lower()
    # the MRO walk (not just the leaf type name) catches jaxlib subclasses
    # of XlaRuntimeError whose leaf name says nothing about the runtime
    if (any(n in _mro_names(err) for n in DEVICE_ERROR_TYPE_NAMES)
            or any(m in low for m in DEVICE_OOM_MARKERS)):
        return CLASS_DEVICE
    if "Connection refused" in msg or "tunnel" in low:
        return CLASS_TRANSPORT
    return CLASS_OTHER


def is_device_oom(err) -> bool:
    """Is this a device OUT-OF-MEMORY specifically (the errors worth an
    evict-all + retry before host degradation), as opposed to any other
    classified device failure (compile bug, dead tunnel) where retrying
    against an emptied HBM would change nothing?"""
    if classify(err) != CLASS_DEVICE:
        return False
    low = str(err).lower()
    return any(m in low for m in DEVICE_OOM_MARKERS)


class ExchangeError(TiDBError):
    """MPP exchange send/recv failed (reference: ErrTiFlashServerTimeout
    9012 — the store-side fragment could not be reached/completed)."""

    code = 9012
    sqlstate = "HY000"


class LeaseExpiredError(TiDBError):
    """A coordinator lease/election was lost mid-operation."""

    code = 8229  # reference: ErrTxnAbortedByGC-adjacent domain errors
    sqlstate = "HY000"


# -- retry kinds ------------------------------------------------------------

class Kind:
    """One retry curve: capped exponential sleep + optional attempt cap
    (reference: the backoff fn table in store/tikv/backoff.go NewBackoffFn)."""

    __slots__ = ("name", "base_ms", "cap_ms", "jitter", "max_attempts")

    def __init__(self, name, base_ms, cap_ms, jitter="full", max_attempts=0):
        self.name = name
        self.base_ms = base_ms
        self.cap_ms = cap_ms
        self.jitter = jitter  # "full" | "equal" | "none"
        self.max_attempts = max_attempts  # 0 = budget-bound only


#: the kind table — names follow the reference's bo* constants
KINDS = {k.name: k for k in [
    # reads waiting out a committing writer's prewrite locks (boTxnLockFast)
    Kind("txnLockFast", base_ms=2, cap_ms=30, jitter="equal"),
    # pessimistic lock waits (boTxnLock)
    Kind("txnLock", base_ms=5, cap_ms=60, jitter="equal"),
    # optimistic commit conflict replay (boTxnConflict-ish)
    Kind("txnRetry", base_ms=1, cap_ms=20, jitter="full"),
    # independent meta txns: autoid / sequence batch allocation
    Kind("autoid", base_ms=0.5, cap_ms=10, jitter="full", max_attempts=20),
    # DDL backfill batch vs concurrent DML
    Kind("ddlBackfill", base_ms=0.5, cap_ms=10, jitter="full",
         max_attempts=20),
    # MPP exchange capacity regrowth (recompile, no sleep — the "retry"
    # is a bigger buffer, not waiting for a remote)
    Kind("exchangeGrow", base_ms=0, cap_ms=0, jitter="none",
         max_attempts=12),
    # MPP exchange send/recv transport failure (boTiFlashRPC)
    Kind("exchangeRetry", base_ms=2, cap_ms=40, jitter="equal",
         max_attempts=6),
    # background-compile RPC/transport failure (executor/compile_service):
    # a flaky remote-compile tunnel is retried on a short curve before the
    # job fails classified and charges the compile-scoped breaker
    Kind("compileRetry", base_ms=5, cap_ms=100, jitter="equal",
         max_attempts=4),
    # WAL fsync failure (kv/wal.py): ONE budgeted retry before the owner
    # aborts the commit — a transient EIO/ENOSPC blip should not abort a
    # durable txn, but a sick disk must fail fast, not spin
    Kind("walSyncRetry", base_ms=5, cap_ms=50, jitter="equal",
         max_attempts=2),
    # network-coordinator transport failure (fabric/coord_net.py): a few
    # short attempts before the client opens its down-window and degrades
    # to local-only admission
    Kind("coordRetry", base_ms=2, cap_ms=50, jitter="equal",
         max_attempts=4),
    # fleet-frontier freshness wait (kv/shared_store.fresh_read_ts): a
    # snapshot blocking until the local replica applies through every
    # live origin's durable commit frontier.  Short sleeps — the tailer
    # normally closes the gap in one TAIL_INTERVAL_S tick; exhaustion is
    # the LOUD stale-read refusal (FreshnessWaitError 9011) and trips
    # the lagging origin's freshness breaker
    Kind("freshnessWait", base_ms=2, cap_ms=40, jitter="equal"),
    # waiting out a foreign DDL owner lease (ddl.ddl_owner_lease): the
    # segment's epoch-fenced DDL cell is held by another worker running
    # a job; poll until it releases or its lease dies
    Kind("ddlOwnerWait", base_ms=20, cap_ms=200, jitter="equal"),
]}
# (no "lease"/"device" kinds yet: campaign losses degrade by skipping the
# round, and device failures route through the circuit breaker, not a
# retry curve — add entries here only when a caller actually backs off)

#: default per-request sleep budget before tidb_backoff_weight scaling
#: (the reference's copNextMaxBackoff is 20s; in-process sleeps are ms-scale
#: so the budget is too)
DEFAULT_BUDGET_MS = 1000.0


class Backoffer:
    """Per-request retry budget (reference: tikv.Backoffer).

    One Backoffer spans one logical request (a statement, a DDL job step,
    an MPP fragment dispatch).  Every retry calls :meth:`backoff`, which
    sleeps per the kind's curve and raises :class:`BackoffExhaustedError`
    — carrying the classified history of everything that went wrong —
    once the sleep budget or the kind's attempt cap is exhausted.

    ``seed`` makes the jitter deterministic for tests that assert on the
    sleep curve (production Backoffers are entropy-seeded; the chaos
    harness's bit-for-bit replays rest on its single-threaded schedule,
    not on retry timing); ``check_killed`` lets KILL and the
    max_execution_time watchdog interrupt a sleeping retry loop.
    """

    def __init__(self, budget_ms: float | None = None, weight: float = 1.0,
                 seed: int | None = None, check_killed=None,
                 sleep: bool = True, wall_clock: bool = False):
        base = DEFAULT_BUDGET_MS if budget_ms is None else float(budget_ms)
        self.budget_ms = base * max(float(weight), 0.0)
        self.slept_ms = 0.0
        self.attempts: dict[str, int] = {}
        self.errors: list[tuple[str, str, str]] = []  # (kind, class, msg)
        self._rng = random.Random(seed)
        self._check_killed = check_killed
        self._sleep = sleep
        # wall_clock: the budget is a hard ELAPSED-time deadline (user-
        # facing lock waits), not just accumulated sleep — retries whose
        # re-execution is itself slow must still stop at the deadline
        self._wall_clock = wall_clock
        self._t0 = time.monotonic()

    # -- construction helpers ------------------------------------------

    @classmethod
    def for_session(cls, session, budget_ms: float | None = None,
                    seed: int | None = None) -> "Backoffer":
        """Budget drawn from the session: scaled by tidb_backoff_weight,
        clamped to the remaining max_execution_time window, interruptible
        by the KILL watchdog (reference: the backoffer created per
        coprocessor request under the stmt context)."""
        weight = 1.0
        try:
            weight = max(float(session.get_sysvar("tidb_backoff_weight")),
                         1.0)
        except Exception:
            pass
        base = DEFAULT_BUDGET_MS if budget_ms is None else float(budget_ms)
        budget = base * weight
        try:
            exec_ms = float(session.get_sysvar("max_execution_time"))
        except Exception:
            exec_ms = 0.0
        if exec_ms > 0:
            # the execution-time cap clamps the WEIGHTED budget: no
            # tidb_backoff_weight setting may stretch retries past it
            budget = min(budget, exec_ms)
        return cls(budget_ms=budget, seed=seed,
                   check_killed=getattr(session, "check_killed", None))

    # -- the core step --------------------------------------------------

    def backoff(self, kind: str, err=None) -> int:
        """Record one failed attempt of `kind` and sleep its curve.
        Returns the attempt number (1-based).  Raises BackoffExhaustedError
        when the attempt cap or the sleep budget is exhausted, chaining
        the triggering error."""
        k = KINDS[kind]
        n = self.attempts.get(kind, 0) + 1
        self.attempts[kind] = n
        cls = ""
        if err is not None:
            cls = classify(err)
            self.errors.append((kind, cls, str(err)))
        if self._check_killed is not None:
            self._check_killed()
        if k.max_attempts and n >= k.max_attempts:
            raise self._exhausted(kind, err, f"{kind} attempt cap "
                                  f"{k.max_attempts} reached")
        sleep_ms = self._sleep_ms(k, n)
        if self._wall_clock:
            elapsed_ms = (time.monotonic() - self._t0) * 1000
            if elapsed_ms + sleep_ms > self.budget_ms:
                raise self._exhausted(kind, err, "deadline "
                                      f"{self.budget_ms:.0f}ms exceeded")
        if self.slept_ms + sleep_ms > self.budget_ms:
            raise self._exhausted(kind, err, "sleep budget "
                                  f"{self.budget_ms:.0f}ms exhausted")
        # span tracing (session/tracing.py): each backoff sleep is an
        # event on the statement's trace with its errno CLASS — "where
        # did the time go" includes retry waits, not just device work
        # (lazy import: this module sits under the session package in
        # the import graph; one branch inside event() when not tracing)
        from ..session.tracing import event as _trace_event
        _trace_event("backoff.sleep", kind=kind, cls=cls,
                     ms=round(sleep_ms, 2), attempt=n)
        if sleep_ms > 0 and self._sleep:
            time.sleep(sleep_ms / 1000.0)
        self.slept_ms += sleep_ms
        if self._check_killed is not None:
            self._check_killed()
        return n

    def _sleep_ms(self, k: Kind, attempt: int) -> float:
        if k.base_ms <= 0:
            return 0.0
        raw = min(k.cap_ms, k.base_ms * (2 ** (attempt - 1)))
        if k.jitter == "full":
            return self._rng.uniform(0, raw)
        if k.jitter == "equal":
            return raw / 2 + self._rng.uniform(0, raw / 2)
        return raw

    def _exhausted(self, kind, err, why) -> BackoffExhaustedError:
        history = "; ".join(f"{k}:{c}:{m}" for k, c, m in self.errors[-8:])
        exc = BackoffExhaustedError(
            f"backoff exhausted ({why}) after {self.attempts.get(kind, 0)} "
            f"{kind} attempts, slept {self.slept_ms:.1f}ms"
            + (f" [errors: {history}]" if history else ""))
        exc.retry_kind = kind
        exc.error_class = classify(err) if err is not None else CLASS_OTHER
        exc.__cause__ = err
        log.warning("backoff exhausted: kind=%s class=%s why=%s",
                    kind, exc.error_class, why)
        return exc

    # -- introspection ---------------------------------------------------

    def total_attempts(self) -> int:
        return sum(self.attempts.values())

    def remaining_ms(self) -> float:
        spent = self.slept_ms
        if self._wall_clock:
            spent = max(spent, (time.monotonic() - self._t0) * 1000)
        return max(self.budget_ms - spent, 0.0)
