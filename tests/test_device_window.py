"""Device window kernel (device_exec.device_window): one lexsort + prefix
scans replace the host's per-partition Python loop (reference:
executor/window.go; MPP window fragments in unistore cophandler)."""

import numpy as np
import pytest

from tidb_tpu.testkit import TestKit
import tidb_tpu.executor.device_exec as de


@pytest.fixture(scope="module")
def tk():
    tk = TestKit()
    tk.must_exec("use test")
    tk.must_exec("create table w (g bigint, s varchar(8), v bigint, "
                 "p decimal(10,2), f double)")
    rng = np.random.default_rng(31)
    rows = []
    for i in range(4000):
        null_v = rng.random() < 0.05
        rows.append(
            f"({int(rng.integers(0, 23))}, 'c{i % 5}', "
            f"{'null' if null_v else int(rng.integers(-50, 500))}, "
            f"{int(rng.integers(0, 90000)) / 100:.2f}, "
            f"{float(rng.uniform(-5, 5)):.4f})")
    for lo in range(0, len(rows), 2000):
        tk.must_exec("insert into w values " + ",".join(rows[lo:lo + 2000]))
    return tk


def _both(tk, sql, expect_device=True):
    calls = []
    orig = de.device_window

    def spy(*a, **k):
        r = orig(*a, **k)
        calls.append(1)
        return r

    de.device_window = spy
    import tidb_tpu.executor.exec_select  # noqa: F401
    try:
        tk.must_exec("set tidb_executor_engine = 'tpu'")
        dev = tk.must_query(sql).rows
    finally:
        de.device_window = orig
    tk.must_exec("set tidb_executor_engine = 'host'")
    host = tk.must_query(sql).rows
    assert _rows_equal(dev, host), f"parity failed: {sql}"
    if expect_device:
        assert calls, "device window kernel did not run"
    return dev


def _rows_equal(a, b):
    """Cell-wise equality with ulp tolerance on float-looking cells: the
    device computes float prefix sums with a different association order
    than the host's per-partition cumsum (test_device_stream makes the
    same allowance for streamed partial sums)."""
    if len(a) != len(b):
        return False
    for ra, rb in zip(a, b):
        if ra == rb:
            continue
        if len(ra) != len(rb):
            return False
        for va, vb in zip(ra, rb):
            if va == vb:
                continue
            try:
                fa, fb = float(va), float(vb)
            except (TypeError, ValueError):
                return False
            if abs(fa - fb) > 1e-9 * max(1.0, abs(fa)):
                return False
    return True


class TestDeviceWindow:
    def test_row_number_rank_dense(self, tk):
        _both(tk, (
            "select g, v, row_number() over (partition by g order by v), "
            "rank() over (partition by g order by v), "
            "dense_rank() over (partition by g order by v) "
            "from w order by g, v, 1, 3"))

    def test_desc_order_and_string_partition(self, tk):
        _both(tk, (
            "select s, v, row_number() over (partition by s order by "
            "v desc, g) from w order by s, v desc, g, 3"))

    def test_running_sum_count_avg(self, tk):
        _both(tk, (
            "select g, v, sum(v) over (partition by g order by v), "
            "count(v) over (partition by g order by v), "
            "avg(p) over (partition by g order by v) "
            "from w order by g, v, 3"))

    def test_partition_total_no_order(self, tk):
        _both(tk, (
            "select g, sum(p) over (partition by g), "
            "min(v) over (partition by g), max(f) over (partition by g), "
            "count(*) over (partition by g) from w order by g, 2, 3, 4"))

    def test_peer_aware_running_frame(self, tk):
        """Equal ORDER BY keys are peers: the running value at a row
        includes its whole peer group (RANGE, not ROWS)."""
        tk.must_exec("create table wp (g bigint, k bigint, v bigint)")
        tk.must_exec("insert into wp values (1,1,10),(1,1,20),(1,2,30),"
                     "(1,2,40),(1,3,50)")
        rows = _both(tk, (
            "select k, sum(v) over (partition by g order by k) from wp "
            "order by k, 2"))
        assert rows == [("1", "30"), ("1", "30"), ("2", "100"),
                        ("2", "100"), ("3", "150")]

    def test_percent_rank_cume_dist(self, tk):
        _both(tk, (
            "select g, v, percent_rank() over (partition by g order by v), "
            "cume_dist() over (partition by g order by v) "
            "from w order by g, v, 3"))

    def test_global_window_no_partition(self, tk):
        _both(tk, (
            "select v, row_number() over (order by v, g) from w "
            "order by v, g"))

    def test_min_max_date_with_nulls(self, tk):
        """MIN/MAX over an int32-backed DATE column with NULLs: the null
        identity must use the device dtype's extremes (int64 extremes wrap
        to -1/0 in int32 — regression: device returned 1969-12-31)."""
        tk.must_exec("create table wd (g bigint, d date)")
        tk.must_exec("insert into wd values (1, '2024-01-01'),(1, null),"
                     "(1, '2024-03-05'),(2, null),(2, '1999-09-09')")
        rows = _both(tk, (
            "select g, min(d) over (partition by g), "
            "max(d) over (partition by g) from wd order by g, 2"))
        assert rows[0][1] == "2024-01-01" and rows[0][2] == "2024-03-05"
        assert rows[-1][1] == "1999-09-09"

    def test_null_computed_partition_key(self, tk):
        """NULL rows of a computed partition key carry arbitrary raw data
        on device — boundary detection must value-mask them or every NULL
        partition splits per row (regression: change() unmasked compare)."""
        tk.must_exec("create table wn (a bigint, b bigint, v bigint)")
        tk.must_exec("insert into wn values (null, 1, 10),(null, 2, 20),"
                     "(null, 3, 30),(1, 1, 40),(1, 2, 50)")
        rows = _both(tk, (
            "select v, count(*) over (partition by a + b) from wn "
            "order by v"))
        # a+b is NULL on three rows -> ONE null partition of size 3
        assert rows[0][1] == "3" and rows[1][1] == "3" and rows[2][1] == "3"

    def test_ntile_falls_back_to_host(self, tk):
        _both(tk, (
            "select g, ntile(3) over (partition by g order by v) from w "
            "order by g, v, 2"), expect_device=False)

    def test_explicit_frame_falls_back(self, tk):
        _both(tk, (
            "select g, sum(v) over (partition by g order by v "
            "rows between 1 preceding and current row) from w "
            "order by g, v, 2"), expect_device=False)
