"""All 22 TPC-H queries end-to-end on small generated data.

Two assertions per query: it executes, and the host and device engines
return identical rows (the parity requirement of the north-star benchmark).
Data is random but deterministic; sizes are small enough for CI yet
non-trivial (joins produce matches, filters pass rows)."""

import numpy as np
import pytest

from tidb_tpu.testkit import TestKit

SF = 0.002  # ~120 orders, ~480 lineitems


def _d(days):
    base = np.datetime64("1992-01-01")
    return str(base + np.timedelta64(int(days), "D"))


def make_tpch_tk(db="tpch_t"):
    """Build a TestKit with the full small TPC-H dataset loaded (shared
    with the MPP-engine parity tests in test_mpp_sql.py)."""
    rng = np.random.default_rng(7)
    tk = TestKit()
    tk.must_exec(f"create database {db}")
    tk.must_exec(f"use {db}")
    tk.must_exec("""create table region (
        r_regionkey bigint primary key, r_name varchar(25),
        r_comment varchar(152))""")
    tk.must_exec("""create table nation (
        n_nationkey bigint primary key, n_name varchar(25),
        n_regionkey bigint, n_comment varchar(152))""")
    tk.must_exec("""create table supplier (
        s_suppkey bigint primary key, s_name varchar(25),
        s_address varchar(40), s_nationkey bigint, s_phone varchar(15),
        s_acctbal decimal(15,2), s_comment varchar(101))""")
    tk.must_exec("""create table part (
        p_partkey bigint primary key, p_name varchar(55),
        p_mfgr varchar(25), p_brand varchar(10), p_type varchar(25),
        p_size bigint, p_container varchar(10),
        p_retailprice decimal(15,2), p_comment varchar(23))""")
    tk.must_exec("""create table partsupp (
        ps_partkey bigint, ps_suppkey bigint, ps_availqty bigint,
        ps_supplycost decimal(15,2), ps_comment varchar(199))""")
    tk.must_exec("""create table customer (
        c_custkey bigint primary key, c_name varchar(25),
        c_address varchar(40), c_nationkey bigint, c_phone varchar(15),
        c_acctbal decimal(15,2), c_mktsegment varchar(10),
        c_comment varchar(117))""")
    tk.must_exec("""create table orders (
        o_orderkey bigint primary key, o_custkey bigint,
        o_orderstatus varchar(1), o_totalprice decimal(15,2),
        o_orderdate date, o_orderpriority varchar(15),
        o_clerk varchar(15), o_shippriority bigint,
        o_comment varchar(79))""")
    tk.must_exec("""create table lineitem (
        l_orderkey bigint, l_partkey bigint, l_suppkey bigint,
        l_linenumber bigint, l_quantity decimal(15,2),
        l_extendedprice decimal(15,2), l_discount decimal(15,2),
        l_tax decimal(15,2), l_returnflag varchar(1),
        l_linestatus varchar(1), l_shipdate date, l_commitdate date,
        l_receiptdate date, l_shipinstruct varchar(25),
        l_shipmode varchar(10), l_comment varchar(44))""")

    regions = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]
    for i, r in enumerate(regions):
        tk.must_exec(f"insert into region values ({i}, '{r}', 'c{i}')")
    nations = [("ALGERIA", 0), ("ARGENTINA", 1), ("BRAZIL", 1),
               ("CANADA", 1), ("EGYPT", 4), ("ETHIOPIA", 0),
               ("FRANCE", 3), ("GERMANY", 3), ("INDIA", 2), ("CHINA", 2),
               ("JAPAN", 2), ("KENYA", 0), ("MOROCCO", 0), ("PERU", 1),
               ("ROMANIA", 3), ("SAUDI ARABIA", 4), ("VIETNAM", 2),
               ("RUSSIA", 3), ("UNITED KINGDOM", 3), ("UNITED STATES", 1)]
    for i, (nm, rk) in enumerate(nations):
        tk.must_exec(f"insert into nation values ({i}, '{nm}', {rk}, 'x')")

    n_supp, n_part, n_cust = 20, 40, 30
    n_orders = int(150_000 * SF * 0.4) or 100
    segments = ["AUTOMOBILE", "BUILDING", "FURNITURE", "MACHINERY",
                "HOUSEHOLD"]
    brands = [f"Brand#{i}{j}" for i in (1, 2, 3, 4, 5) for j in (1, 2, 3)]
    types_ = [f"{a} {b} {c}" for a in ("STANDARD", "SMALL", "MEDIUM",
                                       "LARGE", "ECONOMY", "PROMO")
              for b in ("ANODIZED", "BURNISHED", "PLATED")
              for c in ("TIN", "NICKEL", "BRASS", "STEEL", "COPPER")][:40]
    containers = ["SM CASE", "SM BOX", "MED BAG", "MED BOX", "LG CASE",
                  "LG BOX", "WRAP CASE", "JUMBO PKG"]
    modes = ["REG AIR", "AIR", "RAIL", "SHIP", "TRUCK", "MAIL", "FOB"]
    instr = ["DELIVER IN PERSON", "COLLECT COD", "NONE", "TAKE BACK RETURN"]
    prios = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]

    for i in range(n_supp):
        bal = round(float(rng.uniform(-999, 9999)), 2)
        comment = ("Customer Complaints xx" if i % 7 == 3 else f"s{i}")
        tk.must_exec(
            f"insert into supplier values ({i}, 'Supplier#{i:09d}', "
            f"'addr{i}', {int(rng.integers(0, 20))}, "
            f"'{int(rng.integers(10, 34))}-{i:07d}', {bal}, '{comment}')")
    for i in range(n_part):
        nm = f"{'forest ' if i % 5 == 0 else ''}thing {i}"
        tk.must_exec(
            f"insert into part values ({i}, '{nm}', 'Manufacturer#{i % 5 + 1}', "
            f"'{brands[i % len(brands)]}', '{types_[i % len(types_)]}', "
            f"{int(rng.integers(1, 50))}, '{containers[i % len(containers)]}', "
            f"{round(float(rng.uniform(900, 2000)), 2)}, 'p{i}')")
        for s in (i % n_supp, (i * 7 + 3) % n_supp):
            tk.must_exec(
                f"insert into partsupp values ({i}, {s}, "
                f"{int(rng.integers(1, 9999))}, "
                f"{round(float(rng.uniform(1, 1000)), 2)}, 'ps{i}_{s}')")
    for i in range(n_cust):
        tk.must_exec(
            f"insert into customer values ({i}, 'Customer#{i:09d}', "
            f"'caddr{i}', {int(rng.integers(0, 20))}, "
            f"'{int(rng.integers(10, 34))}-{i:07d}', "
            f"{round(float(rng.uniform(-999, 9999)), 2)}, "
            f"'{segments[i % 5]}', 'c{i}')")

    lineno = 0
    for i in range(n_orders):
        cust = int(rng.integers(0, n_cust))
        odate = int(rng.integers(0, 2400))
        status = "F" if odate < 1200 else "O"
        tk.must_exec(
            f"insert into orders values ({i}, {cust}, '{status}', "
            f"{round(float(rng.uniform(1000, 400000)), 2)}, '{_d(odate)}', "
            f"'{prios[i % 5]}', 'Clerk#{i % 10:09d}', 0, 'o{i}')")
        for _l in range(int(rng.integers(1, 5))):
            lineno += 1
            part = int(rng.integers(0, n_part))
            supp = (part + (lineno % 2) * 7 + (0 if lineno % 2 == 0 else 3)) % n_supp
            sdate = odate + int(rng.integers(1, 120))
            cdate = odate + int(rng.integers(30, 90))
            rdate = sdate + int(rng.integers(1, 30))
            rf = "R" if rng.random() < 0.3 else ("A" if rng.random() < 0.4
                                                 else "N")
            tk.must_exec(
                f"insert into lineitem values ({i}, {part}, {supp}, "
                f"{lineno}, {int(rng.integers(1, 51))}, "
                f"{round(float(rng.uniform(901, 95000)), 2)}, "
                f"0.0{int(rng.integers(0, 9))}, 0.0{int(rng.integers(0, 8))}, "
                f"'{rf}', '{'F' if status == 'F' else 'O'}', '{_d(sdate)}', "
                f"'{_d(cdate)}', '{_d(rdate)}', '{instr[lineno % 4]}', "
                f"'{modes[lineno % 7]}', 'l{lineno}')")
    return tk


@pytest.fixture(scope="module")
def tk():
    return make_tpch_tk()


def both(tk, sql):
    tk.must_exec("set tidb_executor_engine = 'host'")
    host = tk.must_query(sql).rows
    tk.must_exec("set tidb_executor_engine = 'tpu'")
    dev = tk.must_query(sql).rows
    tk.must_exec("set tidb_executor_engine = 'auto'")
    assert host == dev, (f"engine divergence\nhost({len(host)}): "
                         f"{host[:5]}\ntpu({len(dev)}): {dev[:5]}")
    return host


def test_q01(tk):
    rows = both(tk, """
        select l_returnflag, l_linestatus, sum(l_quantity) as sum_qty,
            sum(l_extendedprice) as sum_base_price,
            sum(l_extendedprice * (1 - l_discount)) as sum_disc_price,
            sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) as sum_charge,
            avg(l_quantity) as avg_qty, avg(l_extendedprice) as avg_price,
            avg(l_discount) as avg_disc, count(*) as count_order
        from lineitem where l_shipdate <= date_sub('1998-12-01', interval 90 day)
        group by l_returnflag, l_linestatus
        order by l_returnflag, l_linestatus""")
    assert rows


def test_q02(tk):
    both(tk, """
        select s_acctbal, s_name, n_name, p_partkey, p_mfgr, s_address,
               s_phone, s_comment
        from part, supplier, partsupp, nation, region
        where p_partkey = ps_partkey and s_suppkey = ps_suppkey
          and p_size = 15 and p_type like '%BRASS'
          and s_nationkey = n_nationkey and n_regionkey = r_regionkey
          and r_name = 'EUROPE'
          and ps_supplycost = (
              select min(ps_supplycost)
              from partsupp, supplier, nation, region
              where p_partkey = ps_partkey and s_suppkey = ps_suppkey
                and s_nationkey = n_nationkey and n_regionkey = r_regionkey
                and r_name = 'EUROPE')
        order by s_acctbal desc, n_name, s_name, p_partkey limit 100""")


def test_q03(tk):
    rows = both(tk, """
        select l_orderkey,
               sum(l_extendedprice * (1 - l_discount)) as revenue,
               o_orderdate, o_shippriority
        from customer, orders, lineitem
        where c_mktsegment = 'BUILDING' and c_custkey = o_custkey
          and l_orderkey = o_orderkey and o_orderdate < '1996-01-01'
          and l_shipdate > '1994-06-01'
        group by l_orderkey, o_orderdate, o_shippriority
        order by revenue desc, o_orderdate limit 10""")
    assert rows


def test_q04(tk):
    rows = both(tk, """
        select o_orderpriority, count(*) as order_count from orders
        where o_orderdate >= '1993-07-01'
          and o_orderdate < date_add('1993-07-01', interval 3 month)
          and exists (select * from lineitem where l_orderkey = o_orderkey
                      and l_commitdate < l_receiptdate)
        group by o_orderpriority order by o_orderpriority""")
    assert rows is not None


def test_q05(tk):
    both(tk, """
        select n_name, sum(l_extendedprice * (1 - l_discount)) as revenue
        from customer, orders, lineitem, supplier, nation, region
        where c_custkey = o_custkey and l_orderkey = o_orderkey
          and l_suppkey = s_suppkey and c_nationkey = s_nationkey
          and s_nationkey = n_nationkey and n_regionkey = r_regionkey
          and r_name = 'ASIA' and o_orderdate >= '1994-01-01'
          and o_orderdate < date_add('1994-01-01', interval 1 year)
        group by n_name order by revenue desc""")


def test_q06(tk):
    rows = both(tk, """
        select sum(l_extendedprice * l_discount) as revenue from lineitem
        where l_shipdate >= '1994-01-01'
          and l_shipdate < date_add('1994-01-01', interval 1 year)
          and l_discount between 0.02 and 0.08 and l_quantity < 24""")
    assert len(rows) == 1


def test_q07(tk):
    both(tk, """
        select supp_nation, cust_nation, l_year, sum(volume) as revenue
        from (select n1.n_name as supp_nation, n2.n_name as cust_nation,
                     year(l_shipdate) as l_year,
                     l_extendedprice * (1 - l_discount) as volume
              from supplier, lineitem, orders, customer,
                   nation n1, nation n2
              where s_suppkey = l_suppkey and o_orderkey = l_orderkey
                and c_custkey = o_custkey and s_nationkey = n1.n_nationkey
                and c_nationkey = n2.n_nationkey
                and ((n1.n_name = 'FRANCE' and n2.n_name = 'GERMANY')
                     or (n1.n_name = 'GERMANY' and n2.n_name = 'FRANCE'))
                and l_shipdate between '1995-01-01' and '1996-12-31'
             ) as shipping
        group by supp_nation, cust_nation, l_year
        order by supp_nation, cust_nation, l_year""")


def test_q08(tk):
    both(tk, """
        select o_year,
               sum(case when nationx = 'BRAZIL' then volume else 0 end)
                   / sum(volume) as mkt_share
        from (select year(o_orderdate) as o_year,
                     l_extendedprice * (1 - l_discount) as volume,
                     n2.n_name as nationx
              from part, supplier, lineitem, orders, customer,
                   nation n1, nation n2, region
              where p_partkey = l_partkey and s_suppkey = l_suppkey
                and l_orderkey = o_orderkey and o_custkey = c_custkey
                and c_nationkey = n1.n_nationkey
                and n1.n_regionkey = r_regionkey and r_name = 'AMERICA'
                and s_nationkey = n2.n_nationkey
                and o_orderdate between '1995-01-01' and '1996-12-31'
             ) as all_nations
        group by o_year order by o_year""")


def test_q09(tk):
    both(tk, """
        select nationx, o_year, sum(amount) as sum_profit
        from (select n_name as nationx, year(o_orderdate) as o_year,
                     l_extendedprice * (1 - l_discount)
                     - ps_supplycost * l_quantity as amount
              from part, supplier, lineitem, partsupp, orders, nation
              where s_suppkey = l_suppkey and ps_suppkey = l_suppkey
                and ps_partkey = l_partkey and p_partkey = l_partkey
                and o_orderkey = l_orderkey and s_nationkey = n_nationkey
                and p_name like '%thing%'
             ) as profit
        group by nationx, o_year order by nationx, o_year desc""")


def test_q10(tk):
    both(tk, """
        select c_custkey, c_name,
               sum(l_extendedprice * (1 - l_discount)) as revenue,
               c_acctbal, n_name, c_address, c_phone, c_comment
        from customer, orders, lineitem, nation
        where c_custkey = o_custkey and l_orderkey = o_orderkey
          and o_orderdate >= '1993-10-01'
          and o_orderdate < date_add('1993-10-01', interval 3 month)
          and l_returnflag = 'R' and c_nationkey = n_nationkey
        group by c_custkey, c_name, c_acctbal, c_phone, n_name,
                 c_address, c_comment
        order by revenue desc limit 20""")


def test_q11(tk):
    both(tk, """
        select ps_partkey, sum(ps_supplycost * ps_availqty) as value_
        from partsupp, supplier, nation
        where ps_suppkey = s_suppkey and s_nationkey = n_nationkey
          and n_name = 'GERMANY'
        group by ps_partkey
        having sum(ps_supplycost * ps_availqty) > (
            select sum(ps_supplycost * ps_availqty) * 0.0001
            from partsupp, supplier, nation
            where ps_suppkey = s_suppkey and s_nationkey = n_nationkey
              and n_name = 'GERMANY')
        order by value_ desc""")


def test_q12(tk):
    both(tk, """
        select l_shipmode,
               sum(case when o_orderpriority = '1-URGENT'
                        or o_orderpriority = '2-HIGH'
                   then 1 else 0 end) as high_line_count,
               sum(case when o_orderpriority <> '1-URGENT'
                        and o_orderpriority <> '2-HIGH'
                   then 1 else 0 end) as low_line_count
        from orders, lineitem
        where o_orderkey = l_orderkey and l_shipmode in ('MAIL', 'SHIP')
          and l_commitdate < l_receiptdate and l_shipdate < l_commitdate
          and l_receiptdate >= '1994-01-01'
          and l_receiptdate < date_add('1994-01-01', interval 1 year)
        group by l_shipmode order by l_shipmode""")


def test_q13(tk):
    both(tk, """
        select c_count, count(*) as custdist
        from (select c_custkey, count(o_orderkey) as c_count
              from customer left outer join orders
                on c_custkey = o_custkey
                and o_comment not like '%special%requests%'
              group by c_custkey) as c_orders
        group by c_count order by custdist desc, c_count desc""")


def test_q14(tk):
    rows = both(tk, """
        select 100.00 * sum(case when p_type like 'PROMO%'
                            then l_extendedprice * (1 - l_discount)
                            else 0 end)
               / sum(l_extendedprice * (1 - l_discount)) as promo_revenue
        from lineitem, part
        where l_partkey = p_partkey and l_shipdate >= '1995-09-01'
          and l_shipdate < date_add('1995-09-01', interval 1 month)""")
    assert len(rows) == 1


def test_q15(tk):
    both(tk, """
        with revenue0 as (
            select l_suppkey as supplier_no,
                   sum(l_extendedprice * (1 - l_discount)) as total_revenue
            from lineitem
            where l_shipdate >= '1996-01-01'
              and l_shipdate < date_add('1996-01-01', interval 3 month)
            group by l_suppkey)
        select s_suppkey, s_name, s_address, s_phone, total_revenue
        from supplier, revenue0
        where s_suppkey = supplier_no
          and total_revenue = (select max(total_revenue) from revenue0)
        order by s_suppkey""")


def test_q16(tk):
    both(tk, """
        select p_brand, p_type, p_size,
               count(distinct ps_suppkey) as supplier_cnt
        from partsupp, part
        where p_partkey = ps_partkey and p_brand <> 'Brand#45'
          and p_type not like 'MEDIUM%'
          and p_size in (49, 14, 23, 45, 19, 3, 36, 9)
          and ps_suppkey not in (select s_suppkey from supplier
                                 where s_comment like '%Customer%Complaints%')
        group by p_brand, p_type, p_size
        order by supplier_cnt desc, p_brand, p_type, p_size""")


def test_q17(tk):
    rows = both(tk, """
        select sum(l_extendedprice) / 7.0 as avg_yearly
        from lineitem, part
        where p_partkey = l_partkey and p_brand = 'Brand#23'
          and p_container = 'MED BOX'
          and l_quantity < (select 0.2 * avg(l_quantity) from lineitem
                            where l_partkey = p_partkey)""")
    assert len(rows) == 1


def test_q18(tk):
    both(tk, """
        select c_name, c_custkey, o_orderkey, o_orderdate, o_totalprice,
               sum(l_quantity)
        from customer, orders, lineitem
        where o_orderkey in (select l_orderkey from lineitem
                             group by l_orderkey
                             having sum(l_quantity) > 100)
          and c_custkey = o_custkey and o_orderkey = l_orderkey
        group by c_name, c_custkey, o_orderkey, o_orderdate, o_totalprice
        order by o_totalprice desc, o_orderdate limit 100""")


def test_q19(tk):
    both(tk, """
        select sum(l_extendedprice * (1 - l_discount)) as revenue
        from lineitem, part
        where (p_partkey = l_partkey and p_brand = 'Brand#12'
               and p_container in ('SM CASE', 'SM BOX')
               and l_quantity >= 1 and l_quantity <= 11
               and p_size between 1 and 5
               and l_shipmode in ('AIR', 'REG AIR')
               and l_shipinstruct = 'DELIVER IN PERSON')
           or (p_partkey = l_partkey and p_brand = 'Brand#23'
               and p_container in ('MED BAG', 'MED BOX')
               and l_quantity >= 10 and l_quantity <= 20
               and p_size between 1 and 10
               and l_shipmode in ('AIR', 'REG AIR')
               and l_shipinstruct = 'DELIVER IN PERSON')""")


def test_q20(tk):
    both(tk, """
        select s_name, s_address from supplier, nation
        where s_suppkey in (
            select ps_suppkey from partsupp
            where ps_partkey in (select p_partkey from part
                                 where p_name like 'forest%')
              and ps_availqty > (
                  select 0.5 * sum(l_quantity) from lineitem
                  where l_partkey = ps_partkey and l_suppkey = ps_suppkey
                    and l_shipdate >= '1994-01-01'
                    and l_shipdate < date_add('1994-01-01', interval 1 year)))
          and s_nationkey = n_nationkey and n_name = 'CANADA'
        order by s_name""")


def test_q21(tk):
    both(tk, """
        select s_name, count(*) as numwait
        from supplier, lineitem l1, orders, nation
        where s_suppkey = l1.l_suppkey and o_orderkey = l1.l_orderkey
          and o_orderstatus = 'F' and l1.l_receiptdate > l1.l_commitdate
          and exists (select * from lineitem l2
                      where l2.l_orderkey = l1.l_orderkey
                        and l2.l_suppkey <> l1.l_suppkey)
          and not exists (select * from lineitem l3
                          where l3.l_orderkey = l1.l_orderkey
                            and l3.l_suppkey <> l1.l_suppkey
                            and l3.l_receiptdate > l3.l_commitdate)
          and s_nationkey = n_nationkey and n_name = 'SAUDI ARABIA'
        group by s_name order by numwait desc, s_name limit 100""")


def test_q22(tk):
    both(tk, """
        select cntrycode, count(*) as numcust, sum(c_acctbal) as totacctbal
        from (select substring(c_phone, 1, 2) as cntrycode, c_acctbal
              from customer
              where substring(c_phone, 1, 2) in
                    ('13', '31', '23', '29', '30', '18', '17')
                and c_acctbal > (select avg(c_acctbal) from customer
                                 where c_acctbal > 0.00
                                   and substring(c_phone, 1, 2) in
                                       ('13', '31', '23', '29', '30',
                                        '18', '17'))
                and not exists (select * from orders
                                where o_custkey = c_custkey)
             ) as custsale
        group by cntrycode order by cntrycode""")
