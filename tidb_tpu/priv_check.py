"""Per-statement privilege requirements (reference:
planner/core/planbuilder.go visitInfo collection + privilege/privileges
RequestVerification at executor build)."""

from __future__ import annotations

from .parser import ast


def _collect_tables(node, out):
    """Every ast.TableName reachable from the statement (FROM clauses,
    subqueries, DML targets). Iterative worklist: no recursion limit to
    fail open past (deep ORM-generated OR-chains are legitimate) and none
    to blow the Python stack."""
    stack = [node]
    while stack:
        n = stack.pop()
        if n is None:
            continue
        if isinstance(n, ast.TableName):
            out.append(n)
            continue
        if isinstance(n, (list, tuple)):
            stack.extend(n)
            continue
        # walk EVERY ast.Node: Join / SubqueryTable / table sources
        # subclass Node directly, not StmtNode/ExprNode — a narrower guard
        # would skip join trees and derived tables entirely (fail-open)
        fields = getattr(n, "__dataclass_fields__", None)
        if fields is None or not isinstance(n, ast.Node):
            continue
        for name in fields:
            stack.append(getattr(n, name))


def _alias_map(session, from_node):
    """alias(lower) -> (db, table_name) for base tables of a FROM tree —
    multi-table DML names its targets by alias."""
    tabs = []
    _collect_tables(from_node, tabs)
    infos = session.infoschema()
    out = {}
    for tn in tabs:
        db = tn.schema or session.current_db()
        if db and infos.has_table(db, tn.name):
            out[(tn.as_name or tn.name).lower()] = (db, tn.name)
    return out


def _update_targets(session, stmt, amap):
    """Exact (db, table) set-targets of a multi-table UPDATE: qualified
    columns name their table; an unqualified column resolves to the unique
    join table carrying it (matching the executor's resolution), falling
    back to all tables only when genuinely ambiguous."""
    infos = session.infoschema()
    out = set()
    for cn, _e in stmt.assignments:
        if cn.table and cn.table.lower() in amap:
            out.add(amap[cn.table.lower()])
            continue
        if not cn.table:
            hits = []
            for db, name in amap.values():
                info = infos.table_by_name(db, name)
                if info.find_column(cn.name) is not None:
                    hits.append((db, name))
            if len(hits) == 1:
                out.add(hits[0])
            else:
                out.update(amap.values())  # ambiguous: conservative
    return out


def check_stmt_privileges(session, stmt):
    priv = session.domain.priv
    user = session.user
    infos = session.infoschema()

    def req_tables(node, p):
        seen = set()
        tabs = []
        _collect_tables(node, tabs)
        for tn in tabs:
            db = (tn.schema or session.current_db()).lower()
            key = (db, tn.name.lower(), p)
            if key in seen:
                continue
            seen.add(key)
            # CTE names / derived aliases aren't catalog tables: only
            # verify names that actually resolve (missing tables fail later
            # with their own error, same as the reference)
            if db and infos.has_table(db, tn.name):
                priv.verify(user, db, tn.name, p)

    if isinstance(stmt, (ast.SelectStmt, ast.SetOprStmt)):
        req_tables(stmt, "select")
    elif isinstance(stmt, ast.InsertStmt):
        db = (stmt.table.schema or session.current_db())
        priv.verify(user, db, stmt.table.name, "insert")
        if stmt.select is not None:
            req_tables(stmt.select, "select")
    elif isinstance(stmt, ast.UpdateStmt):
        # write priv on the TARGET only; subquery sources need just SELECT
        if isinstance(stmt.table, ast.TableName):
            priv.verify(user, stmt.table.schema or session.current_db(),
                        stmt.table.name, "update")
        else:
            # multi-table form: UPDATE only on the exact set-target tables
            # (resolved through their aliases); the rest of the join is a
            # read
            amap = _alias_map(session, stmt.table)
            for db, name in _update_targets(session, stmt, amap):
                priv.verify(user, db, name, "update")
            req_tables(stmt.table, "select")
        req_tables(stmt.where, "select")
        req_tables(stmt.assignments, "select")
    elif isinstance(stmt, ast.DeleteStmt):
        if stmt.targets:
            # targets may be ALIASES of join tables: resolve before
            # verifying, or an aliased target escapes the check entirely
            amap = _alias_map(session, stmt.table)
            for tn in stmt.targets:
                key = (tn.as_name or tn.name).lower()
                if key in amap:
                    db, name = amap[key]
                    priv.verify(user, db, name, "delete")
                else:
                    db = tn.schema or session.current_db()
                    if db and infos.has_table(db, tn.name):
                        priv.verify(user, db, tn.name, "delete")
            req_tables(stmt.table, "select")
        elif isinstance(stmt.table, ast.TableName):
            priv.verify(user, stmt.table.schema or session.current_db(),
                        stmt.table.name, "delete")
        req_tables(stmt.where, "select")
    elif isinstance(stmt, ast.CreateTableStmt):
        db = stmt.table.schema or session.current_db()
        priv.verify(user, db, stmt.table.name, "create")
    elif isinstance(stmt, ast.CreateViewStmt):
        priv.verify(user, stmt.view.schema or session.current_db(),
                    stmt.view.name, "create")
        # the definer must be able to read every underlying table
        # (reference: MySQL requires SELECT on each column accessed)
        req_tables(stmt.select, "select")
    elif isinstance(stmt, ast.DropTableStmt):
        for tn in stmt.tables:
            priv.verify(user, tn.schema or session.current_db(),
                        tn.name, "drop")
    elif isinstance(stmt, ast.TruncateTableStmt):
        priv.verify(user, stmt.table.schema or session.current_db(),
                    stmt.table.name, "drop")
    elif isinstance(stmt, (ast.CreateIndexStmt, ast.DropIndexStmt)):
        priv.verify(user, stmt.table.schema or session.current_db(),
                    stmt.table.name, "index")
    elif isinstance(stmt, ast.AlterTableStmt):
        priv.verify(user, stmt.table.schema or session.current_db(),
                    stmt.table.name, "alter")
        for spec in stmt.specs:
            if spec[0] == "exchange_partition":
                # the other table's contents are swapped away wholesale
                # (reference: MySQL requires ALTER/INSERT/CREATE/DROP on
                # both tables)
                other = spec[2]
                odb = other.schema or session.current_db()
                for p in ("alter", "insert", "drop"):
                    priv.verify(user, odb, other.name, p)
    elif isinstance(stmt, ast.RecoverTableStmt):
        # resurrecting a dropped table is at least as powerful as
        # CREATE + the DROP it undoes
        db = stmt.table.schema or session.current_db()
        priv.verify(user, db, stmt.new_name or stmt.table.name, "create")
        priv.verify(user, db, stmt.table.name, "drop")
    elif isinstance(stmt, ast.CreateDatabaseStmt):
        priv.verify(user, stmt.name, "", "create")
    elif isinstance(stmt, ast.DropDatabaseStmt):
        priv.verify(user, stmt.name, "", "drop")
    elif isinstance(stmt, ast.RenameTableStmt):
        for old, new in stmt.pairs:
            priv.verify(user, old.schema or session.current_db(),
                        old.name, "alter")
            priv.verify(user, old.schema or session.current_db(),
                        old.name, "drop")
            priv.verify(user, new.schema or session.current_db(),
                        new.name, "create")
    elif isinstance(stmt, (ast.GrantStmt, ast.RevokeStmt)):
        # the grant option AND every granted privilege must be HELD at the
        # target level (reference: executor/grant.go ActivePrivileges) —
        # db/table-scoped grant option delegates only within its scope
        from .privilege import DB_PRIVS, PRIVS
        gdb = "" if stmt.db == "*" else (stmt.db or session.current_db())
        gtable = "" if stmt.table == "*" else stmt.table
        priv.verify(user, gdb, gtable, "grant")
        # ALL expands to the privileges that EXIST at the target level —
        # requiring SUPER for a db-scoped GRANT ALL would defeat delegation
        level = PRIVS if (not gdb and not gtable) else DB_PRIVS
        names = [p for p in level if p != "grant"] \
            if "all" in stmt.privs else stmt.privs
        for p in names:
            if p in ("usage", "grant"):
                continue
            priv.verify(user, gdb, gtable, p)
    elif isinstance(stmt, (ast.CreateUserStmt, ast.DropUserStmt,
                           ast.AlterUserStmt)):
        priv.verify(user, "mysql", "user", "grant")
    elif isinstance(stmt, ast.BRIEStmt):
        priv.verify(user, "", "", "super")  # BACKUP/RESTORE are super-only
    elif isinstance(stmt, (ast.CreateBindingStmt, ast.DropBindingStmt)):
        if stmt.is_global:
            # global bindings steer every session's plans (reference:
            # bindinfo requires SUPER for GLOBAL scope)
            priv.verify(user, "", "", "super")
    elif isinstance(stmt, ast.ExplainStmt):
        # EXPLAIN ANALYZE executes the inner statement — same read checks
        req_tables(stmt.stmt, "select")
    elif isinstance(stmt, ast.TraceStmt):
        # TRACE SELECT executes the inner statement outside _dispatch
        req_tables(stmt.stmt, "select")
    # SHOW / SET / admin / txn-control: unrestricted
