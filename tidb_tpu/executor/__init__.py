"""Executor layer (reference: executor/ — builder.go maps plans to executors;
here build_executor maps logical operators to chunk-at-a-time executors whose
hot kernels run on host numpy or device jax per the session's engine flag)."""

from .exec_select import build_executor, QueryExecutor

__all__ = ["build_executor", "QueryExecutor"]
