"""MySQL wire packet framing and primitives (reference: server/packetio.go
+ server/util.go — 4-byte header [3-byte little-endian length, 1-byte
sequence id], length-encoded integers/strings, 16MB continuation)."""

from __future__ import annotations

import struct

MAX_PAYLOAD = 0xFFFFFF


class PacketIO:
    """Sequenced packet reader/writer over a socket-like object."""

    def __init__(self, sock):
        self.sock = sock
        self.seq = 0

    def reset_seq(self):
        self.seq = 0

    def _read_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("connection closed")
            buf += chunk
        return buf

    def read_packet(self) -> bytes:
        payload = b""
        while True:
            header = self._read_exact(4)
            length = header[0] | (header[1] << 8) | (header[2] << 16)
            self.seq = (header[3] + 1) & 0xFF
            payload += self._read_exact(length)
            if length < MAX_PAYLOAD:
                return payload

    def write_packet(self, payload: bytes):
        data = payload
        while True:
            chunk, data = data[:MAX_PAYLOAD], data[MAX_PAYLOAD:]
            header = struct.pack("<I", len(chunk))[:3] + bytes([self.seq])
            self.sock.sendall(header + chunk)
            self.seq = (self.seq + 1) & 0xFF
            if len(chunk) < MAX_PAYLOAD:
                return


def lenenc_int(n: int) -> bytes:
    if n < 251:
        return bytes([n])
    if n < 1 << 16:
        return b"\xfc" + struct.pack("<H", n)
    if n < 1 << 24:
        return b"\xfd" + struct.pack("<I", n)[:3]
    return b"\xfe" + struct.pack("<Q", n)


def lenenc_str(s: bytes) -> bytes:
    return lenenc_int(len(s)) + s


def read_lenenc_int(buf: bytes, pos: int):
    first = buf[pos]
    if first < 251:
        return first, pos + 1
    if first == 0xFC:
        return struct.unpack_from("<H", buf, pos + 1)[0], pos + 3
    if first == 0xFD:
        return (buf[pos + 1] | (buf[pos + 2] << 8)
                | (buf[pos + 3] << 16)), pos + 4
    if first == 0xFE:
        return struct.unpack_from("<Q", buf, pos + 1)[0], pos + 9
    raise ValueError(f"invalid lenenc int prefix {first:#x}")


def read_lenenc_str(buf: bytes, pos: int):
    n, pos = read_lenenc_int(buf, pos)
    return buf[pos:pos + n], pos + n


def read_nul_str(buf: bytes, pos: int):
    end = buf.index(0, pos)
    return buf[pos:end], end + 1
