"""JAX version compatibility for the distributed execution path.

The MPP layer is written against the modern `jax.shard_map` API
(`check_vma=` relaxation flag).  Older jax releases (<= 0.4.x, the
pinned toolchain on some hosts) ship the same primitive as
`jax.experimental.shard_map.shard_map` with the flag spelled
`check_rep=`.  A bare import error here used to take down EVERY
aggregate query — the executor imports mpp_exec unconditionally — which
is exactly the ungraceful-death mode this resilience layer exists to
remove, so the shim degrades across versions instead.
"""

from __future__ import annotations

import functools
import inspect

try:  # jax >= 0.6: top-level export, `check_vma` flag
    from jax import shard_map as _shard_map
except ImportError:  # jax <= 0.4.x: experimental module, `check_rep` flag
    from jax.experimental.shard_map import shard_map as _shard_map

_PARAMS = set(inspect.signature(_shard_map).parameters)


@functools.wraps(_shard_map)
def shard_map(*args, **kw):
    if "check_vma" in kw and "check_vma" not in _PARAMS:
        relaxed = kw.pop("check_vma")
        if "check_rep" in _PARAMS:
            kw["check_rep"] = relaxed
    elif "check_rep" in kw and "check_rep" not in _PARAMS:
        relaxed = kw.pop("check_rep")
        if "check_vma" in _PARAMS:
            kw["check_vma"] = relaxed
    return _shard_map(*args, **kw)
