"""Executor layer (reference: executor/ — builder.go maps plans to executors;
here build_executor maps logical operators to chunk-at-a-time executors whose
hot kernels run on host numpy or device jax per the session's engine flag)."""

from .exec_select import build_executor as _build_executor_tree
from .exec_select import QueryExecutor


def build_executor(plan, ctx, stats=None) -> QueryExecutor:
    """Root entry: (re)sets the statement-scoped engine pin from the
    plan's /*+ READ_FROM_STORAGE(...) */ hint before building the tree —
    unconditionally, so a previous statement's pin never leaks into an
    unhinted one (the attr survives plan-cache hits because it lives on
    the cached plan)."""
    ctx.stmt_engine_hint = getattr(plan, "engine_hint", None)
    return _build_executor_tree(plan, ctx, stats)


__all__ = ["build_executor", "QueryExecutor"]
