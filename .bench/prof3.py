import os, sys, time
sys.path.insert(0, "/root/repo")
os.environ["JAX_PLATFORMS"] = "cpu"
import jax; jax.config.update("jax_platforms", "cpu")
import importlib
b = importlib.import_module("bench")
from tidb_tpu.testkit import TestKit
tk = TestKit()
tk.must_exec("set tidb_mem_quota_query = 0")
b.gen_all(tk, 0.1)
sub = ("select l_orderkey from lineitem group by l_orderkey "
       "having sum(l_quantity) > 300")
for eng in ("tpu", "host"):
    tk.must_exec(f"set tidb_executor_engine = '{eng}'")
    for i in range(3):
        t0 = time.perf_counter()
        r = tk.must_query(sub)
        print(f"{eng} run {i}: {time.perf_counter()-t0:.4f}s rows={len(r.rows)}", flush=True)
