"""Window function execution (reference: executor/window.go; default frame
semantics: with ORDER BY = RANGE UNBOUNDED PRECEDING..CURRENT ROW, peers
included; without = whole partition)."""

import pytest

from tidb_tpu.testkit import TestKit


@pytest.fixture()
def tk():
    tk = TestKit()
    tk.must_exec("create table w (id int primary key, g varchar(8), v int)")
    rows = [(1, "a", 10), (2, "a", 20), (3, "a", 20), (4, "a", 40),
            (5, "b", 5), (6, "b", 15), (7, "c", 7)]
    vals = ",".join(f"({i},'{g}',{v})" for i, g, v in rows)
    tk.must_exec(f"insert into w values {vals}")
    return tk


def test_row_number(tk):
    tk.must_query(
        "select id, row_number() over (partition by g order by v, id) "
        "from w order by id").check([
            ("1", "1"), ("2", "2"), ("3", "3"), ("4", "4"),
            ("5", "1"), ("6", "2"), ("7", "1")])


def test_rank_and_dense_rank_with_ties(tk):
    tk.must_query(
        "select id, rank() over (partition by g order by v), "
        "dense_rank() over (partition by g order by v) "
        "from w order by id").check([
            ("1", "1", "1"), ("2", "2", "2"), ("3", "2", "2"),
            ("4", "4", "3"), ("5", "1", "1"), ("6", "2", "2"),
            ("7", "1", "1")])


def test_running_sum_peer_aware(tk):
    # ties (v=20 twice in partition a) are peers: both rows see the sum
    # through the end of the peer group
    tk.must_query(
        "select id, sum(v) over (partition by g order by v) "
        "from w order by id").check([
            ("1", "10"), ("2", "50"), ("3", "50"), ("4", "90"),
            ("5", "5"), ("6", "20"), ("7", "7")])


def test_partition_aggregate_without_order(tk):
    tk.must_query(
        "select id, sum(v) over (partition by g), "
        "count(*) over (partition by g) from w order by id").check([
            ("1", "90", "4"), ("2", "90", "4"), ("3", "90", "4"),
            ("4", "90", "4"), ("5", "20", "2"), ("6", "20", "2"),
            ("7", "7", "1")])


def test_global_window_no_partition(tk):
    tk.must_query(
        "select id, count(*) over () from w where id <= 3 order by id"
    ).check([("1", "3"), ("2", "3"), ("3", "3")])


def test_lead_lag(tk):
    tk.must_query(
        "select id, lag(v) over (partition by g order by id), "
        "lead(v, 1, -1) over (partition by g order by id) "
        "from w order by id").check([
            ("1", None, "20"), ("2", "10", "20"), ("3", "20", "40"),
            ("4", "20", "-1"), ("5", None, "15"), ("6", "5", "-1"),
            ("7", None, "-1")])


def test_first_last_value(tk):
    tk.must_query(
        "select id, first_value(v) over (partition by g order by id), "
        "last_value(v) over (partition by g) from w order by id").check([
            ("1", "10", "40"), ("2", "10", "40"), ("3", "10", "40"),
            ("4", "10", "40"), ("5", "5", "15"), ("6", "5", "15"),
            ("7", "7", "7")])


def test_min_max_running(tk):
    tk.must_query(
        "select id, min(v) over (partition by g order by id), "
        "max(v) over (partition by g order by id) from w order by id"
    ).check([
        ("1", "10", "10"), ("2", "10", "20"), ("3", "10", "20"),
        ("4", "10", "40"), ("5", "5", "5"), ("6", "5", "15"),
        ("7", "7", "7")])


def test_ntile(tk):
    tk.must_query(
        "select id, ntile(2) over (order by id) from w order by id").check([
            ("1", "1"), ("2", "1"), ("3", "1"), ("4", "1"),
            ("5", "2"), ("6", "2"), ("7", "2")])


def test_avg_window(tk):
    r = tk.must_query(
        "select id, avg(v) over (partition by g) from w "
        "where g = 'b' order by id")
    assert [row[1] for row in r.rows] == ["10", "10"]


def test_window_over_aggregate(tk):
    """Windows evaluate over the grouped rows (SQL eval order)."""
    tk.must_query(
        "select g, sum(v), rank() over (order by sum(v) desc) "
        "from w group by g order by g").check([
            ("a", "90", "1"), ("b", "20", "2"), ("c", "7", "3")])


def test_window_in_expression(tk):
    tk.must_query(
        "select id, row_number() over (order by id) * 10 from w "
        "where id <= 2 order by id").check([("1", "10"), ("2", "20")])


def test_multiple_specs_stack(tk):
    tk.must_query(
        "select id, row_number() over (partition by g order by id), "
        "count(*) over () from w where id >= 6 order by id").check([
            ("6", "1", "2"), ("7", "1", "2")])


def test_window_explain_shows_node(tk):
    rows = tk.must_query(
        "explain select row_number() over (order by v) from w").rows
    assert any("Window" in r[0] for r in rows)


def test_rows_frame_sliding_sum(tk):
    tk.must_query(
        "select id, sum(v) over (order by id rows between 1 preceding "
        "and current row) from w where g = 'a' order by id").check([
            ("1", "10"), ("2", "30"), ("3", "40"), ("4", "60")])


def test_rows_frame_centered(tk):
    tk.must_query(
        "select id, count(*) over (order by id rows between 1 preceding "
        "and 1 following) from w where g = 'a' order by id").check([
            ("1", "2"), ("2", "3"), ("3", "3"), ("4", "2")])


def test_rows_frame_whole_partition_range(tk):
    tk.must_query(
        "select id, sum(v) over (partition by g order by id range between "
        "unbounded preceding and unbounded following) from w order by id"
    ).check([("1", "90"), ("2", "90"), ("3", "90"), ("4", "90"),
             ("5", "20"), ("6", "20"), ("7", "7")])


def test_rows_frame_first_last_value(tk):
    tk.must_query(
        "select id, first_value(v) over (order by id rows between "
        "1 preceding and current row), last_value(v) over (order by id "
        "rows between current row and 1 following) "
        "from w where g = 'a' order by id").check([
            ("1", "10", "20"), ("2", "10", "20"),
            ("3", "20", "40"), ("4", "20", "40")])


def test_range_offset_frame_rejected(tk):
    e = tk.exec_error(
        "select sum(v) over (order by id range between 1 preceding "
        "and current row) from w")
    assert "RANGE frames" in str(e)


def test_ntile_zero_rejected(tk):
    e = tk.exec_error("select ntile(0) over (order by id) from w")
    assert "Incorrect arguments" in str(e)


def test_nth_value_zero_rejected(tk):
    e = tk.exec_error("select nth_value(v, 0) over (order by id) from w")
    assert "Incorrect arguments" in str(e)


def test_frames_distinct_in_dedup(tk):
    """Same function text with different frames must produce different
    columns."""
    tk.must_query(
        "select sum(v) over (order by id rows between 1 preceding and "
        "current row), sum(v) over (order by id rows between current row "
        "and 1 following) from w where g = 'b' order by id").check([
            ("5", "20"), ("20", "15")])


def test_explain_analyze_streamed_child_stats(tk):
    rows = tk.must_query(
        "explain analyze select v from w order by v").rows
    scan = next(r for r in rows if "TableScan" in r[0])
    assert scan[1].isdigit() and int(scan[1]) == 7


def test_rows_unbounded_not_peer_aware(tk):
    """ROWS UNBOUNDED PRECEDING..CURRENT ROW is row-exact even with tied
    order keys (unlike the peer-aware default RANGE frame)."""
    tk.must_query(
        "select id, sum(v) over (partition by g order by v rows between "
        "unbounded preceding and current row) from w where g = 'a' "
        "order by v, id").check([
            ("1", "10"), ("2", "30"), ("3", "50"), ("4", "90")])
