"""Device (TPU) execution paths for the hot operators.

The fused scan→filter→aggregate pipeline: when a HashAgg sits directly on a
TableScan, the pushed-down filters, the aggregate input expressions and the
grouping all compile into ONE jitted XLA program — the host only dict-encodes
strings and reads back `capacity`-bounded results. This is the engine-side
realization of the reference's coprocessor pushdown (the whole DAG executes
storage-side there, device-side here).
"""

from __future__ import annotations

import collections
import time as _time

import numpy as np
import jax
import jax.numpy as jnp

from ..errors import TiDBError
from ..expression import phys_kind, K_DEC, K_FLOAT, K_STR, K_DATE
from ..expression.core import Column as ExprColumn
from ..ops import device as dev
from ..ops.device import DeviceUnsupported
from ..sqltypes import POW10
from ..utils.chunk import Chunk, Column, np_dtype_for


def engine_mode(ctx) -> str:
    # a statement-scoped /*+ READ_FROM_STORAGE(...) */ pin outranks the
    # session sysvar (set per root executor build; see executor/__init__)
    eh = getattr(ctx, "stmt_engine_hint", None)
    if eh:
        return eh
    try:
        return ctx.get_sysvar("tidb_executor_engine")
    except Exception:
        return "auto"


def run_device(ctx, fn, /, *args, shape="agg", batch_key=None, **kw):
    """Dispatch one device fragment through the serving admission layer
    (executor/scheduler.py), the circuit breaker (executor/circuit.py)
    and the device-runtime supervisor (executor/supervisor.py) — the four
    layers every fragment passes, in order: ADMISSION (may this fragment
    occupy the shared device now?) → SUPERVISOR deadline → BREAKER →
    RESIDENCY budget.

    Admission: the fragment holds a scheduler ticket for the duration of
    the device call — weighted fair queueing across resource groups
    (`tidb_resource_group`), bounded queue depth, per-tenant running
    caps.  A refusal (queue full / wait timeout, classified
    DeviceAdmissionError 9009) degrades this fragment to the host engine
    exactly like an OPEN breaker — overload means host and device serve
    DIFFERENT work concurrently, not an error.  `batch_key` (the
    compiled-pipeline identity of the fragment, when the dispatch site
    can compute it cheaply) lets queued same-shaped fragments coalesce
    onto one scheduling slot, sharing the compiled program and resident
    uploads cross-session.

    An OPEN breaker degrades to the host engine up front
    (DeviceUnsupported → the caller's existing fallback), and a
    classified device/transport failure — an XLA runtime error, a dead
    remote-compile tunnel, an injected fault — records into the breaker
    and ALSO degrades instead of killing the query.  DeviceUnsupported
    and TiDBError pass through untouched: "this fragment doesn't fit the
    device" and genuine user errors are not health signals.

    When a deadline is in force (`tidb_device_call_timeout` sysvar or a
    running `max_execution_time` window) the fragment executes on a
    supervised worker thread: a backend HANG raises a classified
    DeviceHangError into the query (recorded against the breaker, so
    repeated hangs trip degradation), the abandoned call is fenced, and
    the wait stays KILL-interruptible even while the backend blocks
    inside a GIL-holding C call.

    A classified device OUT-OF-MEMORY walks the recovery ladder before
    degrading: evict every residency-tracked HBM upload
    (ops/residency.recover_oom) → retry the fragment ONCE against the
    emptied device → only then record the failure and degrade to host.
    Transient HBM pressure (another session's working set, a one-off
    giant intermediate) costs one re-upload instead of a cooldown.

    `shape` scopes the breaker per fragment class (agg / join / window):
    one failing shape cools down without degrading healthy paths.

    Under the serving fabric (tidb_tpu/fabric) a batch_key'd dispatch
    first consults the FLEET fragment-dedup table: identical concurrent
    fragments — same structural batch key AND same input-chunk content
    hash — anywhere in the fleet dispatch ONE device call; followers
    wait (before admission, so they hold no device slot) and map the
    leader's result page back in.  No fleet, no batch key, or no
    hashable input -> the plain dispatch below."""
    if batch_key is not None:
        from ..fabric import state as fabric_state
        ded = fabric_state.dedup_handle()
        if ded is not None:
            kh = ded.key_hash(batch_key, args)
            if kh is not None:
                return ded.coalesce(
                    ctx, shape, kh,
                    lambda: _run_device_dispatch(ctx, fn, args, kw, shape,
                                                 batch_key))
    return _run_device_dispatch(ctx, fn, args, kw, shape, batch_key)


def _run_device_dispatch(ctx, fn, args, kw, shape, batch_key):
    """The admitted dispatch (layer 1 onward) for one fragment — the
    fabric dedup leader's compute path, and the whole of run_device
    outside a fleet."""
    from ..errors import DeviceAdmissionError
    from ..fabric import perf as fabric_perf
    from ..session import tracing
    from . import scheduler
    group = scheduler.resource_group(ctx)
    scheduler.attach(ctx)
    # shared fragment-perf store feed (fabric/perf.py): this dispatch's
    # admission wait, sync-compile share and device wall time accumulate
    # under the fragment's (sig, bucket) — fleet-mergeable observe-only
    # data, buffered locally and flushed off the hot path
    psig, pbucket = fabric_perf.dispatch_key(batch_key, shape)
    with tracing.span("device.dispatch", shape=shape, group=group):
        ta0 = _time.perf_counter()
        try:
            ticket = scheduler.admit(ctx, shape=shape, batch_key=batch_key)
        except DeviceAdmissionError as e:
            # load pressure, not device ill-health: no breaker charge —
            # the fragment runs on the host engine (per-tenant gauge
            # records it; the trace carries the classified reason)
            scheduler.note_degradation(group)
            tracing.event("host_degraded", reason="admission", shape=shape)
            raise DeviceUnsupported(
                f"device admission refused for {shape} fragment "
                f"(resource group '{group}'; degraded to host engine): "
                f"{e}") from e
        finally:
            # refusals contribute too: the timeout wait a refused
            # fragment paid is exactly the tail this series exists for
            fabric_perf.note(psig, pbucket, "device", "admission_wait",
                             _time.perf_counter() - ta0)
        t0 = _time.perf_counter()
        c0 = _tls_stats()["compile_s"]
        try:
            return _run_device_admitted(ctx, fn, args, kw, shape, group)
        finally:
            scheduler.release(ticket)
            # per-fragment latency histogram (session/observe.py
            # HIST_BUCKETS): one admitted dispatch end-to-end — in the
            # finally so FAILED dispatches (supervisor-deadline hangs,
            # post-OOM degrades) contribute too; the pathological
            # latencies are exactly the p99 this series exists to show
            dt = _time.perf_counter() - t0
            # the TLS pipe-stats mirror attributes exactly this thread's
            # sync-compile seconds to this dispatch (concurrent sessions
            # can't cross-charge — same contract as pipe_cache_stats)
            dc = _tls_stats()["compile_s"] - c0
            if dc > 0:
                fabric_perf.note(psig, pbucket, "device", "compile", dc)
            fabric_perf.note(psig, pbucket, "device", "dispatch", dt)
            obs = getattr(getattr(ctx, "domain", None), "observe", None)
            if obs is not None and hasattr(obs, "observe_hist"):
                obs.observe_hist("device_dispatch_seconds", dt)


def _run_device_admitted(ctx, fn, args, kw, shape, group):
    """Layers 2-4 (supervisor deadline → breaker → residency) for a
    fragment that holds its admission ticket."""
    from ..errors import DeviceHangError
    from ..ops import residency
    from ..session import tracing
    from ..utils.backoff import (classify, is_device_oom, CLASS_DEVICE,
                                 CLASS_EXCHANGE, CLASS_FAULT,
                                 CLASS_TRANSPORT)
    from . import supervisor
    from .circuit import get_breaker
    br = get_breaker(ctx, shape=shape)
    sid = getattr(ctx, "conn_id", None)
    if not br.allow(session=sid, group=group):
        tracing.event("host_degraded", reason="breaker_open", shape=shape)
        raise DeviceUnsupported(
            f"device circuit open for {shape} fragments (cooling down; "
            "fragment degraded to host engine)")
    residency.attach(ctx)  # budget sysvar + tenant + observe gauge sink
    deadline_s, fence_on_expiry = supervisor.deadline_for(ctx)
    oom_retried = False
    while True:
        try:
            out = supervisor.call_supervised(
                fn, args, kw, deadline_s=deadline_s, ctx=ctx, shape=shape,
                fence_on_expiry=fence_on_expiry)
        except DeviceHangError as e:
            # the hang IS a health verdict: count it toward opening the
            # breaker, then surface the classified error — the query fails
            # (its device call is still in flight; a silent host fallback
            # would hide that the deadline fired) but the NEXT queries
            # degrade once the breaker trips
            br.record_failure(e, session=sid, group=group)
            tracing.event("breaker.recorded", cls="hang", shape=shape)
            raise
        except (DeviceUnsupported, TiDBError):
            # no health verdict: if this fragment held the HALF_OPEN probe
            # slot, free it — otherwise the breaker wedges with no prober
            br.release_probe(session=sid)
            raise
        except (KeyboardInterrupt, SystemExit):
            # Ctrl-C mid-probe must not wedge the breaker in HALF_OPEN
            br.release_probe(session=sid)
            raise
        except Exception as e:
            cls = classify(e)
            if cls not in (CLASS_DEVICE, CLASS_TRANSPORT, CLASS_FAULT,
                           CLASS_EXCHANGE):
                # an UNCLASSIFIED error is a programming bug, not a device
                # health signal: surface it instead of silently degrading
                br.release_probe(session=sid)
                raise
            if not oom_retried and is_device_oom(e):
                # OOM ladder step 1+2: evict all cached HBM, ONE retry.
                # No breaker charge yet — an OOM the eviction absorbs is
                # pressure, not device ill-health; a SECOND failure of any
                # class takes the normal degrade path below.
                oom_retried = True
                tracing.event("oom_ladder", step="evict_all_retry",
                              shape=shape)
                residency.recover_oom(e)
                continue
            br.record_failure(e, session=sid, group=group)
            tracing.event("host_degraded", reason=cls, shape=shape)
            raise DeviceUnsupported(
                f"device failure ({cls}): {e}") from e
        br.record_success(session=sid)
        return out


def want_device(ctx, n_rows: int) -> bool:
    mode = engine_mode(ctx)
    if mode == "host":
        return False
    if mode == "tpu":
        return True
    try:  # auto: device dispatch overhead beneath this row floor
        floor = int(ctx.get_sysvar("tidb_device_dispatch_rows"))
    except Exception:
        floor = 65536
    if floor <= 0:
        # derive the floor from the calibrated cost constants (one
        # currency for planner placement AND runtime gating — with
        # uncalibrated defaults this is the historical 65536)
        from ..planner.cost_model import CostModel
        floor = CostModel.from_ctx(ctx).device_breakeven_rows()
    return n_rows >= floor


#: jitted fused pipelines keyed by plan signature — the whole
#: filter→keys→values→aggregate program is ONE XLA computation, traced once
#: and re-dispatched on later executions (reference analog: coprocessor DAG
#: compiled per plan digest). LRU-bounded; each entry pins strong refs to
#: the string dictionaries whose codes are baked into the traced program.
#: Key components that depend on a dictionary use its CONTENT signature
#: (utils/chunk.py dict_content_sig), not its id: a delta append re-encodes
#: into new dictionary objects whose content — and therefore every baked
#: code LUT — is usually unchanged, and shape bucketing (ops/device.py
#: bucket_rows) keeps the traced array shapes stable too, so the compiled
#: program survives the delta.
_PIPE_CACHE: "collections.OrderedDict" = collections.OrderedDict()
_PIPE_CACHE_MAX = 256

#: compiled-fragment cache observability: hits/misses are _PIPE_CACHE
#: lookups; traces counts actual jax retraces (one per XLA compile);
#: compile_s is wall time of dispatches that triggered a trace. Surfaced
#: per query through EXPLAIN ANALYZE annotations and bench.py compile_s.
#: Process totals are lock-guarded; a THREAD-LOCAL mirror gives per-query
#: delta attribution that concurrent sessions can't cross-charge.
import threading as _threading

_PIPE_STATS = {"hits": 0, "misses": 0, "traces": 0, "compiles": 0,
               "compile_s": 0.0,
               # background mirror: compile work done on compile-service
               # worker threads lands here instead, so per-query compile_s
               # stays the SYNC cost (bench splits sync_compile_s vs
               # bg_compile_s from these)
               "bg_traces": 0, "bg_compiles": 0, "bg_compile_s": 0.0,
               # compile-mode counters (executor/compile_service.py):
               # how each pipeline resolution was served — drives the
               # per-fragment compile_mode EXPLAIN ANALYZE annotation
               "mode_cached": 0, "mode_prewarmed": 0,
               "mode_async_pending": 0, "mode_sync": 0}
_PIPE_LOCK = _threading.Lock()
_PIPE_TLS = _threading.local()

#: process-total keys a compile-service worker thread redirects into the
#: bg_* mirror (its own TLS keeps the plain names so observed_jit's
#: trace-delta compile detection still works on that thread)
_BG_ROUTED = frozenset({"traces", "compiles", "compile_s"})


def mark_bg_thread(on: bool = True) -> bool:
    """Mark the CALLING thread as a background compile worker: its
    trace/compile charges route to the process bg_* keys (query-path
    compile accounting must not absorb background work).  Returns the
    previous mark so a SCOPED marking (compile_service._do_compile under
    a supervisor deadline runs on a REUSED supervisor worker thread)
    can restore it — a lingering mark would mis-route that worker's
    later query-fragment compiles into the bg mirror."""
    prev = getattr(_PIPE_TLS, "bg", False)
    _PIPE_TLS.bg = on
    return prev


def _tls_stats() -> dict:
    st = getattr(_PIPE_TLS, "stats", None)
    if st is None:
        st = _PIPE_TLS.stats = {"hits": 0, "misses": 0, "traces": 0,
                                "compiles": 0, "compile_s": 0.0,
                                "mode_cached": 0, "mode_prewarmed": 0,
                                "mode_async_pending": 0, "mode_sync": 0}
    return st


def _bump(key, amt=1):
    pkey = key
    if key in _BG_ROUTED and getattr(_PIPE_TLS, "bg", False):
        pkey = "bg_" + key
    with _PIPE_LOCK:
        _PIPE_STATS[pkey] += amt
    st = _tls_stats()
    if key in st:
        st[key] += amt


def pipe_cache_stats(thread_local: bool = False) -> dict:
    """Cache/compile counters: process-wide totals by default, or this
    thread's own (for before/after deltas around one dispatch — the
    process totals would charge a concurrent session's compile here)."""
    if thread_local:
        return dict(_tls_stats())
    with _PIPE_LOCK:
        return dict(_PIPE_STATS)


def _pipe_cache_get(key):
    # OrderedDict LRU mutation is NOT thread-safe; concurrent sessions
    # (threaded chaos, server connections) share this cache, so every
    # structural touch happens under the stats lock
    with _PIPE_LOCK:
        hit = _PIPE_CACHE.get(key)
        if hit is not None:
            _PIPE_CACHE.move_to_end(key)
    if hit is None:
        _bump("misses")
        return None
    _bump("hits")
    return hit[0]


def _pipe_cache_put(key, fn, dict_refs):
    with _PIPE_LOCK:
        _PIPE_CACHE[key] = (fn, dict_refs)
        if len(_PIPE_CACHE) > _PIPE_CACHE_MAX:
            _PIPE_CACHE.popitem(last=False)


def acquire_pipeline(key, build, dict_refs, *, ctx=None, args=None,
                     spec=None, shape="agg", sig="", ladder=True):
    """THE pipeline resolution chokepoint: every compiled query pipeline
    (scan-agg, streamed, window, join fragment, MPP) resolves through
    here — cache hit, or the compile service (async background compile /
    persistent-index warm start / sync build; executor/compile_service).

    `build` is a zero-arg builder returning the jitted fn; `args` the
    concrete call arguments (shapes recorded for background warming and
    the prewarm ladder — pass them whenever the dispatch site has them).
    Raises DeviceUnsupported when the fragment should run host-side
    while its executable compiles in the background."""
    fn = _pipe_cache_get(key)
    if fn is not None:
        from . import compile_service
        compile_service.note_hit(key)
        from ..session import tracing
        tracing.event("compile.cached", shape=shape)
        return fn
    from . import compile_service
    return compile_service.obtain(key, build, dict_refs, ctx=ctx,
                                  args=args, spec=spec, shape=shape,
                                  sig=sig, ladder=ladder)


def _count_trace():
    """Called from INSIDE a traced pipeline body: runs once per jax
    retrace (i.e. per XLA compile), never on a cached dispatch — and on
    the thread that dispatched, so the thread-local mirror attributes the
    compile to the right query."""
    _bump("traces")


def _charge_compile_s(seconds):
    _bump("compiles")
    _bump("compile_s", seconds)
    from ..session import tracing
    tracing.event("compile.xla", s=round(seconds, 4))
    if not getattr(_PIPE_TLS, "bg", False):
        # sync compiles only: the query path PAID this wall time, so it
        # belongs in the scrapeable per-layer histogram — background
        # builds overlap host serving and would poison the p99
        from . import compile_service
        compile_service.observe_hist("sync_compile_seconds", seconds)


# kernel-layer observability hooks: installing these makes
# ops/device.observed_jit meter retraces and compile seconds into the
# stats above — for the fused pipelines here AND the standalone
# join-match / topk / graft-agg kernels (one wrapper implementation,
# hook-wired so ops/device never imports the executor layer)
dev._trace_cb = _count_trace
dev._tls_traces = lambda: _tls_stats()["traces"]
dev._charge_compile = _charge_compile_s


def _timed_jit(fn):
    """jax.jit with compile accounting (ops/device.observed_jit with the
    hooks above installed): a dispatch that triggered a retrace — the
    body calls _count_trace — charges its wall time (trace + XLA compile
    + dispatch) to compile_s; cached dispatches pay only a counter
    read."""
    return dev.observed_jit(fn)


def _dc_sig(dc) -> str:
    """Content signature of a DeviceCol's dictionary for cache keys (falls
    back to id() only when no backing host column exists)."""
    if dc.dictionary is None:
        return ""
    hc = dc.host_col
    if hc is not None:
        try:
            return hc.dict_sig()
        except Exception:
            pass
    from ..utils.chunk import dict_content_sig
    return dict_content_sig(dc.dictionary)


def _expr_sig(e) -> str:
    """Structural signature of an expression (type-aware; reprs alone drop
    decimal scales, which change the traced program)."""
    from ..expression.core import Constant as _Const, ScalarFunc as _SF
    ft = e.ftype
    base = f"{ft.tp}.{ft.scale}"
    if isinstance(e, ExprColumn):
        return f"c{e.idx}:{base}"
    if isinstance(e, _Const):
        return f"k{e.value!r}:{base}"
    if isinstance(e, _SF):
        extra = f"|{e.extra!r}" if e.extra is not None else ""
        return (f"{e.op}({','.join(_expr_sig(a) for a in e.args)})"
                f"{extra}:{base}")
    # apply-subqueries etc. never run on device
    raise DeviceUnsupported(f"{type(e).__name__} in device fragment")


def _build_pipeline(cond_fns, key_fns, n_keys, val_plan, agg_ops,
                    capacity, pack, raw_tail=False):
    """Close the compiled expression fns over one traceable program and jit
    it: mask, keys, values and the aggregate all fuse into a single XLA
    executable — no eager op dispatch between operators.

    The program takes `(env, n_live)` where env arrays may be BUCKET-PADDED
    past the live rows (ops/device.py bucket_rows): rows at positions >=
    n_live are masked out before the aggregate, so padding can never
    survive a filter or contribute to any group. n_live is a traced
    scalar — within-bucket row-count changes re-dispatch without a
    retrace.

    raw_tail: stop before the in-kernel aggregate and return the
    evaluated (key_cols, key_nulls, val_cols, val_nulls, mask) rows —
    the CPU-backend streamed path aggregates them in numpy (see
    _merge_states_host: the XLA-CPU group-by pays in the packed key
    span; a host reduceat over one block is row-proportional)."""

    def pipeline(env, n_live):
        _count_trace()
        first = next(iter(env.values()))[0]
        n = first.shape[0]
        live = jnp.arange(n) < n_live
        if cond_fns:
            mask = None
            for f in cond_fns:
                d, nl = f(env)
                m = (d != 0) & ~nl
                mask = m if mask is None else (mask & m)
            mask = jnp.broadcast_to(mask, (n,)) & live
        else:
            mask = live
        key_cols, key_nulls = [], []
        for f in key_fns:
            d, nl = dev.broadcast_1d(*f(env), n)
            key_cols.append(d.astype(jnp.int64))
            key_nulls.append(nl)
        if not key_cols:
            key_cols = [jnp.zeros(n, dtype=jnp.int64)]
            key_nulls = [jnp.zeros(n, dtype=bool)]
        val_cols, val_nulls = [], []
        # one eval per distinct compiled expr: AVG plans (sum, count) over
        # the SAME fn — sharing the traced (d, nl) lets the kernel's
        # identity-based null-row dedup fire and XLA CSE the value rows
        evaled = {}
        for f, conv in val_plan:
            hit = evaled.get(id(f))
            if hit is None:
                hit = dev.broadcast_1d(*f(env), n)
                evaled[id(f)] = hit
            d, nl = hit
            if conv == "int":
                d = d.astype(jnp.int64)
            val_cols.append(d)
            val_nulls.append(nl)
        if raw_tail:
            return (tuple(key_cols), tuple(key_nulls), tuple(val_cols),
                    tuple(val_nulls), mask)
        return dev._agg_impl(tuple(key_cols), tuple(key_nulls),
                             tuple(val_cols), tuple(val_nulls), mask,
                             n_keys=n_keys, agg_ops=agg_ops,
                             capacity=capacity, pack=pack)

    return _timed_jit(pipeline)


def _agg_used_columns(plan, conds) -> set:
    used = set()
    for e in plan.group_exprs:
        e.columns_used(used)
    for d in plan.aggs:
        for a in d.args:
            a.columns_used(used)
    for c in conds:
        c.columns_used(used)
    return used


def _agg_struct_parts(plan, conds) -> list:
    """The STRUCTURAL part of a scan-agg fragment's signature (conds,
    group exprs, agg descs — everything except dictionary content).  One
    helper feeds both _agg_sig and agg_batch_key so the admission batch
    key can never silently diverge from the compiled-pipeline identity
    it claims to prefix."""
    return (
        [_expr_sig(c) for c in conds] + ["|g|"] +
        [_expr_sig(e) for e in plan.group_exprs] + ["|a|"] +
        [f"{d.name}:{_expr_sig(d.args[0]) if d.args else ''}"
         for d in plan.aggs])


def _agg_sig(plan, conds, dcols) -> tuple:
    """(signature string, dictionary refs) for the pipeline cache — shared
    by the whole-table and streamed paths so their caches never diverge.
    Dictionaries contribute their CONTENT signature: a delta append that
    re-encodes the same value set must hit the cached pipeline."""
    sig = ";".join(
        _agg_struct_parts(plan, conds) +
        [f"{idx}:{_dc_sig(dc)}" for idx, dc in sorted(dcols.items())
         if dc.dictionary is not None])
    refs = tuple(dc.dictionary for dc in dcols.values()
                 if dc.dictionary is not None)
    return sig, refs


def agg_batch_key(plan, conds, n_rows: int, ctx=None):
    """Cheap admission-batching identity for a scan-agg fragment: the
    structural (plan sig, bucket shape) prefix of the compiled-pipeline
    cache key — dictionary CONTENT sigs are deliberately omitted (they
    require the columns in hand; admission runs before the upload).
    Queued fragments sharing this key coalesce onto one scheduling slot
    (executor/scheduler.py): identical keys re-dispatch the same cached
    XLA program against the same bucket, so N concurrent same-shaped
    queries cost ~one device call.  None when the fragment contains
    expressions the device can't sign (it won't batch, just queue)."""
    try:
        sig = ";".join(_agg_struct_parts(plan, conds))
        return ("agg", sig, dev.bucket_rows(n_rows, dev.shape_buckets(ctx)))
    except Exception:
        return None


def device_agg(plan, chunk: Chunk, conds, ctx=None) -> Chunk:
    """Fused filter+group+aggregate on device. Raises DeviceUnsupported to
    trigger host fallback."""
    from ..utils import failpoint
    # chaos/breaker hook: a `panic` here models a device runtime failure
    # (dead tunnel, OOM) at the fragment boundary
    failpoint.inject("device-agg-exec")
    n = chunk.num_rows
    if n == 0:
        raise DeviceUnsupported("empty input")
    # canonicalize the traced shape: upload at the row bucket, mask live
    # rows in-program — a within-bucket delta reuses the compiled pipeline
    nb = dev.bucket_rows(n, dev.shape_buckets(ctx))
    used = _agg_used_columns(plan, conds)
    dcols = {}
    env = {}
    for idx in used:
        dc = dev.to_device_col(chunk.columns[idx], bucket=nb)
        dcols[idx] = dc
        env[idx] = (dc.data, dc.nulls)
    if not env:
        raise DeviceUnsupported("no columns")
    from ..session import tracing
    tracing.event("device.upload", cols=len(env), bucket=nb, rows=n)

    # --- host-side planning only below (no device ops until dispatch) ---
    cond_fns = [dev.compile_expr(c, dcols) for c in conds]
    (key_fns, key_meta, key_pack, val_plan, agg_ops,
     slots) = _plan_agg(plan, dcols)
    n_keys = max(len(key_fns), 1)
    sig_exprs, dict_refs = _agg_sig(plan, conds, dcols)
    est = _estimate_groups(plan, n, ctx)
    capacity = dev.next_pow2(min(n, max(est, 16)))
    while True:
        key = (sig_exprs, capacity, key_pack, tuple(agg_ops))
        cap = capacity

        def build(cap=cap):
            return _build_pipeline(cond_fns, key_fns, n_keys, val_plan,
                                   tuple(agg_ops), cap, key_pack)
        fn = acquire_pipeline(key, build, dict_refs, ctx=ctx,
                              args=(env, np.int64(n)), shape="agg",
                              sig=sig_exprs)
        f = AggFetch(fn(env, np.int64(n)), topn=resolve_topn(plan, slots))
        ng = f.ng
        if ng <= capacity:
            break
        capacity = dev.next_pow2(ng)
    if ng == 0 and not plan.group_exprs:
        # global aggregate over zero kept rows still yields ONE row
        # (count=0, sum/min/max NULL) — host path has the special case
        raise DeviceUnsupported("empty global aggregate")
    body = f.body()
    return _assemble_agg(plan, key_meta, slots, dcols, body, f.out_rows)


#: below this payload, one batched round trip beats two (tunnel latency
#: ~150ms dominates small copies)
_SMALL_FETCH_BYTES = 1 << 18


class AggFetch:
    """Device→host fetch of an _agg_impl result tree, minimizing tunnel
    bytes: big capacities read the group count (+ any convergence scalars)
    first and then ONE batched copy of just the live [:ng] prefix — a
    capacity-sized fetch of a TopN-bound or overflowing result wastes most
    of the payload. Small results keep the single batched round trip
    (device_exec historically batched everything for exactly that reason).
    On a retry (caller sees ng/overflow and recompiles) the body is never
    fetched at all."""

    def __init__(self, agg_out, extras=(), topn=None):
        (self._keys, self._key_nulls, self._results, self._result_nulls,
         n_groups, _valid) = agg_out
        arrays = (*self._keys, *self._key_nulls, *self._results,
                  *self._result_nulls)
        self._cap = int(arrays[0].shape[0]) if arrays else 0
        row_bytes = sum(a.dtype.itemsize for a in arrays) or 1
        self._topn = topn
        self._body = None
        self.out_rows = None  # rows in body(); set on fetch
        if self._cap * row_bytes <= _SMALL_FETCH_BYTES:
            out = jax.device_get(
                (agg_out[:4], n_groups, tuple(extras)))
            self._body, ngv, self.extras = out
            self.ng = self.out_rows = int(ngv)
        else:
            out = jax.device_get((n_groups, tuple(extras)))
            self.ng = int(out[0])
            self.extras = out[1]

    def body(self):
        """(key_out, key_null_out, results, result_nulls): the live groups
        — or, under a TopN annotation, just the top candidate groups in
        TopN-key order (selected on-device, so the tunnel carries k rows
        instead of millions)."""
        if self._body is None:
            k = min(max(self.ng, 1), self._cap)
            if self._topn is not None and self.ng > self._topn[1]:
                specs, kf = self._topn
                idx = _topk_indices(self._keys, self._key_nulls,
                                    self._results, self._result_nulls,
                                    self.ng, self._cap, specs, kf)
                self._body = jax.device_get(tuple(
                    tuple(a[idx] for a in t)
                    for t in (self._keys, self._key_nulls,
                              self._results, self._result_nulls)))
                self.out_rows = kf
                return self._body

            def sl(t):
                return tuple(a[:k] for a in t)
            self._body = jax.device_get(
                (sl(self._keys), sl(self._key_nulls),
                 sl(self._results), sl(self._result_nulls)))
            self.out_rows = self.ng
        return self._body


#: jitted top-k kernels by (cap, k, spec, dtype) signature.  Structural
#: access happens under _PIPE_LOCK, same as _PIPE_CACHE: the fence path
#: (supervisor._reinit_backend) clears this cache while executor threads
#: install into it, and an install racing the clear unlocked would
#: re-publish an executable pinning the torn-down PJRT client
_TOPK_CACHE: dict = {}


def _topk_indices(keys, key_nulls, results, result_nulls, ng, cap, specs,
                  k):
    """Indices of the top-k live groups ordered by `specs` (device-side).
    specs: (("key"|"res", j, desc), ...). Null ordering matches the host
    comparator (ops/host.py sort_indices: NULLs first ASC, last DESC);
    descending ints use bitwise-not (exact, unlike negation at int64.min);
    rows past ng sort behind everything."""
    by = []
    for src, j, _desc in specs:
        d = keys[j] if src == "key" else results[j]
        nl = key_nulls[j] if src == "key" else result_nulls[j]
        by.append((d, nl))
    sig = (cap, k, tuple((s[0], s[2]) for s in specs),
           tuple(d.dtype.str for d, _ in by))
    with _PIPE_LOCK:
        fn = _TOPK_CACHE.get(sig)
    if fn is None:
        descs = [s[2] for s in specs]

        def run(by_arrays, ng_):
            _count_trace()
            lex = []  # jnp.lexsort: minor → major
            for (d, nl), desc in zip(reversed(by_arrays), reversed(descs)):
                if jnp.issubdtype(d.dtype, jnp.floating):
                    v = -d if desc else d
                else:
                    v = d.astype(jnp.int64)
                    if desc:
                        v = ~v
                lex.append(jnp.where(nl, 0, v))
                lex.append(jnp.where(nl, 1 if desc else 0,
                                     0 if desc else 1))
            lex.append(jnp.arange(cap) >= ng_)  # live rows first
            return jnp.lexsort(lex)[:k]

        with _PIPE_LOCK:
            # setdefault: a racing builder's kernel wins once installed
            # (both are valid; one object keeps jit's internal cache hot)
            fn = _TOPK_CACHE.setdefault(sig, _timed_jit(run))
    return fn(by, ng)


def resolve_topn(plan, slots):
    """plan.topn_fetch (agg-OUTPUT indices) → AggFetch specs over the
    device result arrays: group keys map 1:1; aggregate outputs map
    through their result slot. None when not annotated or unmappable."""
    tf = getattr(plan, "topn_fetch", None)
    if not tf or not plan.group_exprs:
        return None
    ngk = len(plan.group_exprs)
    specs = []
    for oi, desc in tf[0]:
        if oi < ngk:
            specs.append(("key", oi, desc))
        else:
            slot = slots[oi - ngk]
            if slot[0] == "avg":
                return None
            specs.append(("res", slot[1], desc))
    return tuple(specs), int(tf[1])


def _plan_agg(plan, dcols):
    """Host-side agg planning shared by the scan-agg pipeline and the join
    fragment: compile group keys and aggregate inputs against `dcols`
    (global column idx → DeviceCol). Returns
    (key_fns, key_meta, key_pack, val_plan, agg_ops, slots)."""
    key_fns = []
    key_meta = []  # (expr, decode dictionary or None)
    key_sizes = []  # dict size for string keys (packing), None otherwise
    for e in plan.group_exprs:
        k = phys_kind(e.ftype)
        if k == K_STR:
            # any string-valued expression: codes into its key dictionary
            # (ops/device.py compile_str_expr — CASE/SUBSTRING/… included)
            fn, key_dict, reps = dev.compile_str_expr(e, dcols)
            key_meta.append((e, reps))
            key_fns.append(fn)
            key_sizes.append(len(key_dict))
        elif k == K_FLOAT:
            raise DeviceUnsupported("float group keys")
        else:
            key_meta.append((e, None))
            key_fns.append(dev.compile_expr(e, dcols))
            key_sizes.append(None)
    if key_fns:
        key_pack = _key_pack(plan.group_exprs, key_sizes, dcols)
    else:
        key_pack = ((1, 0),)

    # aggregate value columns + op names; avg = sum + count pair
    val_plan, agg_ops = [], []
    slots = []  # per desc: ("plain", j) | ("avg", j_sum, j_cnt) | ("strcol", j, col)
    for desc in plan.aggs:
        if desc.distinct:
            # COUNT(DISTINCT x): the sorted kernel counts value runs per
            # group (ops/device.py cnt_dist). Other distinct aggs (and
            # multi-arg forms) stay host-side.
            if (desc.name == "count" and len(desc.args) == 1
                    and phys_kind(desc.args[0].ftype)
                    not in (K_FLOAT, K_STR)):
                val_plan.append((dev.compile_expr(desc.args[0], dcols),
                                 "int"))
                agg_ops.append("cnt_dist")
                slots.append(("plain", len(val_plan) - 1))
                continue
            if (desc.name == "count" and len(desc.args) == 1
                    and phys_kind(desc.args[0].ftype) == K_STR):
                # dict codes are value-faithful: distinct codes ==
                # distinct strings
                fn, _kd, _reps = dev.compile_str_expr(desc.args[0], dcols)
                val_plan.append((fn, "int"))
                agg_ops.append("cnt_dist")
                slots.append(("plain", len(val_plan) - 1))
                continue
            raise DeviceUnsupported("distinct agg on device")
        arg = desc.args[0] if desc.args else None
        name = desc.name
        if name == "count":
            val_plan.append((dev.compile_expr(arg, dcols), "int"))
            agg_ops.append("count")
            slots.append(("plain", len(val_plan) - 1))
            continue
        if name not in ("sum", "avg", "min", "max", "first_row"):
            raise DeviceUnsupported(f"agg {name} on device")
        k = phys_kind(arg.ftype)
        if k == K_STR and name in ("min", "max", "first_row"):
            # key dictionaries are sorted → code order == value order
            fn, _key_dict, reps = dev.compile_str_expr(arg, dcols)
            val_plan.append((fn, "int"))
            agg_ops.append({"min": "min", "max": "max",
                            "first_row": "first"}[name])
            slots.append(("strcol", len(val_plan) - 1, reps))
            continue
        if k == K_STR:
            raise DeviceUnsupported("string sum/avg")
        f = dev.compile_expr(arg, dcols)
        is_float = k == K_FLOAT
        if name in ("min", "max", "first_row"):
            val_plan.append((f, "raw"))
            agg_ops.append({"min": "min", "max": "max",
                            "first_row": "first"}[name])
            slots.append(("plain", len(val_plan) - 1))
        elif name == "sum":
            val_plan.append((f, "raw"))
            agg_ops.append("sum_f" if is_float else "sum_i")
            slots.append(("plain", len(val_plan) - 1))
        else:  # avg
            val_plan.append((f, "raw"))
            agg_ops.append("sum_f" if is_float else "sum_i")
            j_sum = len(val_plan) - 1
            val_plan.append((f, "raw" if is_float else "int"))
            agg_ops.append("count")
            slots.append(("avg", j_sum, len(val_plan) - 1))
    return key_fns, key_meta, key_pack, val_plan, agg_ops, slots


def _assemble_agg(plan, key_meta, slots, dcols, out_host, ng):
    """Device agg outputs (already copied to host) → result Chunk."""
    from .agg_cache import note_agg_pass
    note_agg_pass()
    key_out, key_null_out, results, result_nulls = out_host
    out_cols = []
    for (e, dictionary), kd, kn in zip(key_meta, key_out, key_null_out):
        kd = np.asarray(kd[:ng])
        kn = np.asarray(kn[:ng])
        if dictionary is not None:
            data = np.where(kn, b"", dictionary[np.clip(kd, 0, len(dictionary) - 1)])
            out_cols.append(Column(e.ftype, data, kn))
        else:
            dt = np_dtype_for(e.ftype)
            out_cols.append(Column(e.ftype, kd.astype(dt), kn))
    if not plan.group_exprs:
        out_cols = []
    for desc, slot in zip(plan.aggs, slots):
        ft = desc.ftype
        if slot[0] == "avg":
            _tag, j_sum, j_cnt = slot
            s = np.asarray(results[j_sum][:ng])
            c = np.asarray(results[j_cnt][:ng])
            nulls = np.asarray(result_nulls[j_sum][:ng])
            if phys_kind(ft) == K_FLOAT:
                vals = s / np.maximum(c, 1)
                out_cols.append(Column(ft, vals, nulls))
            else:
                arg = desc.args[0]
                from .agg_cache import note_avg_partial
                note_avg_partial(s.astype(object), c)
                s_arg = arg.ftype.scale if phys_kind(arg.ftype) == K_DEC else 0
                shift = POW10[ft.scale - s_arg]
                num = s.astype(object) * shift
                den = np.maximum(c, 1).astype(object)
                sign = np.where(num < 0, -1, 1)
                q = (2 * np.abs(num) + den) // (2 * den)
                vals = np.array([int(x) for x in sign * q], dtype=np.int64)
                out_cols.append(Column(ft, vals, nulls))
            continue
        if slot[0] == "strcol":
            _tag, j, dictionary = slot  # decode dict captured at plan time
            codes = np.asarray(results[j][:ng])
            nulls = np.asarray(result_nulls[j][:ng])
            data = np.where(nulls, b"", dictionary[np.clip(codes, 0, len(dictionary) - 1)])
            out_cols.append(Column(ft, data, nulls))
            continue
        _tag, j = slot
        vals = np.asarray(results[j][:ng])
        nulls = np.asarray(result_nulls[j][:ng])
        if desc.name == "count":
            nulls = np.zeros(ng, dtype=bool)
        dt = np_dtype_for(ft)
        if dt is not object and vals.dtype != dt:
            vals = vals.astype(dt)
        out_cols.append(Column(ft, vals, nulls))
    if not out_cols:
        raise DeviceUnsupported("agg with no outputs")
    return Chunk(out_cols)


_DATE_PACK = (24, 1 << 22)  # MySQL DATE days: [-354285, 2932896] + margin

_EPOCH_DATE = np.datetime64("1970-01-01")


def _expr_bounds(e, dcols):
    """Host-known (min, max) of an integer-kinded group expression, from
    the cached column min/max (utils/chunk.py Column.minmax). Bare columns
    read it directly; YEAR(col) maps bounds through the monotone
    conversion. None when unknown — the caller falls back to the generic
    (multi-sort) agg path."""
    if dcols is None:
        return None
    from ..expression.core import ScalarFunc as _SF
    if isinstance(e, ExprColumn):
        dc = dcols.get(e.idx)
        if dc is None or dc.host_col is None or dc.dictionary is not None:
            return None
        return dc.host_col.minmax()
    if (isinstance(e, _SF) and e.op == "year"
            and isinstance(e.args[0], ExprColumn)
            and phys_kind(e.args[0].ftype) == K_DATE):
        b = _expr_bounds(e.args[0], dcols)
        if b is None:
            return None

        def to_year(days):
            return int(str((_EPOCH_DATE + np.timedelta64(days, "D")
                            ).astype("datetime64[Y]")))
        return to_year(b[0]), to_year(b[1])
    return None


def _key_pack(group_exprs, key_sizes, dcols=None):
    """Static (bits, offset) per group key when every key's value range is
    known a priori — dict codes (cardinality = key dictionary size, from
    _plan_agg), host column min/max for bare keys and YEAR() (cached on
    the Column, so the bound is exact per table version), and DATE days
    (bounded by MySQL's DATE domain) as the date fallback. Enables the
    single-argsort packed path in _agg_kernel. None when any key is
    unbounded or the total exceeds 62 bits."""
    pack = []
    total = 0
    for e, size in zip(group_exprs, key_sizes):
        k = phys_kind(e.ftype)
        if k == K_STR and size is not None:
            bits = max(int(size + 1).bit_length(), 1)
            pack.append((bits, 0))
        else:
            b = _expr_bounds(e, dcols)
            if b is not None:
                mn, mx = b
                span = mx - mn + 1
                pack.append((max((span + 1).bit_length(), 1), -mn))
            elif k == K_DATE:
                pack.append(_DATE_PACK)
            else:
                return None
        total += pack[-1][0]
    if total > 62:
        return None
    return tuple(pack)


def _estimate_groups(plan, n, ctx=None):
    """Group-count bound for the agg kernel's static capacity: product of
    the group columns' ANALYZE NDVs (reference: statistics-driven agg
    cardinality, planner/core/stats.go), falling back to 64 per key, both
    capped at the input size. With a multi-key GROUP BY the NDV product
    overshoots the true joint cardinality, but overshoot only pads the
    sort — undershoot costs a recompile."""
    if not plan.group_exprs:
        return 1
    from ..planner.optimizer import _expr_ndv
    est = 1
    for e in plan.group_exprs:
        nd = None
        if ctx is not None:
            try:
                nd = _expr_ndv(plan.child, e, ctx, n)
            except Exception:
                nd = None
        est *= int(nd * 2) if nd else 64
    return min(est, n)


_MERGE_OPS = {"count": "sum_i", "sum_i": "sum_i", "sum_f": "sum_f",
              "min": "min", "max": "max", "first": "first"}


def device_agg_streaming(plan, chunk: Chunk, conds, batch_rows: int,
                         ctx=None, allow_single=False) -> Chunk:
    """Streamed fused filter+group+aggregate: the input is cut into
    `batch_rows` blocks; each block's columns transfer to HBM and run the
    SAME jitted partial-agg program while the next block's transfer is
    queued (async dispatch = the cop-iterator worker overlap, reference:
    store/copr/coprocessor.go:399); per-block partial states stay on
    device and one merge kernel + one device_get finish the query.

    Device memory is bounded by batch_rows + n_blocks*capacity instead of
    the full table — the long-operand scaling path (SURVEY §5)."""
    n = chunk.num_rows
    if n == 0:
        raise DeviceUnsupported("empty input")
    if batch_rows <= 0 or (n <= batch_rows and not allow_single):
        # whole-input kernel is cheaper — except for paged inputs, whose
        # memmap slices must flow through here regardless of block count
        raise DeviceUnsupported("input fits one batch")
    used = _agg_used_columns(plan, conds)
    if not used:
        raise DeviceUnsupported("no columns")

    # full-column dictionaries (cached on the parent Column): batch slices
    # share codes, so group keys agree across blocks
    col_arrays = {}
    dcols = {}
    for idx in used:
        col = chunk.columns[idx]
        if col.is_object():
            from ..utils.collate import is_ci
            if is_ci(col.ftype.collate):
                codes, key_dict, reps = col.dict_encode_ci(col.ftype.collate)
                col_arrays[idx] = (codes, col.nulls)
                dcols[idx] = dev.DeviceCol(None, None, col.ftype,
                                           dictionary=key_dict, reps=reps,
                                           host_col=col)
            else:
                codes, uniq = col.dict_encode()
                col_arrays[idx] = (codes, col.nulls)
                dcols[idx] = dev.DeviceCol(None, None, col.ftype,
                                           dictionary=uniq, host_col=col)
        else:
            col_arrays[idx] = (col.data, col.nulls)
            dcols[idx] = dev.DeviceCol(None, None, col.ftype,
                                        host_col=col)

    cond_fns = [dev.compile_expr(c, dcols) for c in conds]
    (key_fns, key_meta, key_pack, val_plan, agg_ops,
     slots) = _plan_agg(plan, dcols)
    n_keys = max(len(key_fns), 1)
    if tuple(agg_ops) == ("cnt_dist",):
        # COUNT(DISTINCT x) streams through pair dedup: each block
        # deduplicates (group, x) PAIRS (an agg whose keys are
        # group+value), and the final cnt_dist over the concatenated
        # pair rows is exact even with cross-block duplicates — the
        # sorted kernel counts distinct value runs per group (reference:
        # the two-phase distinct agg, executor/aggregate.go partial
        # dedup + final count)
        return _stream_count_distinct(plan, conds, chunk, col_arrays,
                                      dcols, cond_fns, key_fns, key_meta,
                                      key_pack, val_plan, slots,
                                      batch_rows, ctx)
    if any(op not in _MERGE_OPS for op in agg_ops):
        # other distinct/non-mergeable partial states can't merge across
        # blocks; the whole-input kernel handles them
        raise DeviceUnsupported("non-mergeable agg in streamed pipeline")
    merge_ops = tuple(_MERGE_OPS[op] for op in agg_ops)
    sig_exprs, dict_refs = _agg_sig(plan, conds, dcols)
    if _want_host_tail(key_pack, batch_rows):
        return _stream_agg_host_tail(
            plan, chunk, conds, batch_rows, ctx, col_arrays, dcols,
            (key_fns, key_meta, key_pack, val_plan, agg_ops, slots),
            merge_ops, sig_exprs, dict_refs, cond_fns)

    est = _estimate_groups(plan, n, ctx)
    capacity = dev.next_pow2(min(batch_rows, max(est, 16)))
    merge_cap = capacity  # grows to the true total on merge overflow
    for _attempt in range(8):
        key = (sig_exprs, "stream", capacity, key_pack, tuple(agg_ops))
        cap = capacity

        def build(cap=cap):
            return _build_pipeline(cond_fns, key_fns, n_keys, val_plan,
                                   tuple(agg_ops), cap, key_pack)
        fn = acquire_pipeline(key, build, dict_refs, ctx=ctx,
                              spec=_stream_spec(col_arrays, batch_rows),
                              shape="agg", sig=sig_exprs, ladder=False)
        k_flush = max(1, _MERGE_BUDGET_ROWS // capacity)
        state = None
        buffered = []
        max_ng = 0
        overflow = False
        for lo in range(0, n, batch_rows):
            hi = min(lo + batch_rows, n)
            # the asarray calls enqueue this block's host→HBM copies; the
            # kernel dispatch below is async, so block k+1's transfer
            # overlaps block k's compute. Every block — the tail included —
            # pads to the SAME batch_rows shape (live rows masked by the
            # traced n_live), so one compiled program serves the whole
            # stream at any input size
            env = {idx: (jnp.asarray(dev.pad_host(d[lo:hi], batch_rows)),
                         jnp.asarray(dev.pad_host(nl[lo:hi], batch_rows,
                                                  True)))
                   for idx, (d, nl) in col_arrays.items()}
            buffered.append(fn(env, np.int64(hi - lo)))
            if len(buffered) >= k_flush:
                # incremental fold: HBM holds at most k_flush partials +
                # the running state, never all n/batch_rows of them
                ngs = [int(g) for g in
                       jax.device_get([p[4] for p in buffered])]
                max_ng = max(max_ng, *ngs)
                if max_ng > capacity:
                    overflow = True
                    break
                state, merge_cap = merge_partial_states(
                    state, buffered, merge_cap, n_keys, len(val_plan),
                    merge_ops, key_pack)
                buffered = []
        if not overflow and buffered:
            ngs = [int(g) for g in jax.device_get([p[4] for p in buffered])]
            max_ng = max(max_ng, *ngs)
            if max_ng <= capacity:
                state, merge_cap = merge_partial_states(
                    state, buffered, merge_cap, n_keys, len(val_plan),
                    merge_ops, key_pack)
                buffered = []
        if overflow or max_ng > capacity:
            capacity = dev.next_pow2(max_ng)
            continue
        break
    else:
        raise DeviceUnsupported("streamed agg capacity did not converge")
    if state is None:
        raise DeviceUnsupported("empty streamed input")
    out = jax.device_get(state[:5])
    key_out, key_null_out, results, result_nulls, n_groups = out
    ng = int(n_groups)
    if ng == 0 and not plan.group_exprs:
        raise DeviceUnsupported("empty global aggregate")
    return _assemble_agg(plan, key_meta, slots, dcols,
                         (key_out, key_null_out, results, result_nulls), ng)


def _want_host_tail(key_pack, block_rows: int) -> bool:
    """CPU backend only: aggregate blocks in numpy when the packed key
    SPAN dwarfs the block — the in-kernel dense-bucket agg pays O(span)
    per block there (SF10 Q18: 67M-slot orderkey space over 4M-row
    pages). A small span (Q1's 6-group flag pair) stays in-kernel, where
    the scatter agg is O(rows) with tiny buckets and the raw rows never
    leave the program."""
    if key_pack is None or jax.default_backend() != "cpu":
        return False
    bits = sum(b for b, _o in key_pack)
    # span > block rows: the dense-bucket pass would touch more slots
    # than there are rows (Q18's 24-bit orderkey space over 4M pages);
    # below that the in-kernel scatter is O(rows) and keeps the raw rows
    # inside the program
    return (1 << bits) > max(block_rows, 1)


def _stream_agg_host_tail(plan, chunk, conds, batch_rows, ctx, col_arrays,
                          dcols, agg_meta_full, merge_ops, sig_exprs,
                          dict_refs, cond_fns):
    """CPU-backend streamed scan-agg: raw-tail pipeline per block + numpy
    partial aggregation + one numpy fold (same shape as the paged join's
    host tail — XLA keeps the fused filter/expression work, the host does
    the row-proportional group-by)."""
    key_fns, key_meta, key_pack, val_plan, agg_ops, slots = agg_meta_full
    n = chunk.num_rows
    n_keys = max(len(key_fns), 1)
    nvals = len(val_plan)
    key = (sig_exprs, "stream-rawtail", key_pack, tuple(agg_ops))

    def build():
        return _build_pipeline(cond_fns, key_fns, n_keys, val_plan,
                               tuple(agg_ops), 1, key_pack, raw_tail=True)
    fn = acquire_pipeline(key, build, dict_refs, ctx=ctx,
                          spec=_stream_spec(col_arrays, batch_rows),
                          shape="agg", sig=sig_exprs, ladder=False)
    states = []
    for lo in range(0, n, batch_rows):
        hi = min(lo + batch_rows, n)
        env = {idx: (jnp.asarray(dev.pad_host(d[lo:hi], batch_rows)),
                     jnp.asarray(dev.pad_host(nl[lo:hi], batch_rows, True)))
               for idx, (d, nl) in col_arrays.items()}
        raw = fn(env, np.int64(hi - lo))
        page = page_singleton_state(raw[0], raw[1], raw[2], raw[3],
                                    raw[4], agg_ops)
        state, _cap = _merge_states_host([page], 16, n_keys, nvals,
                                         merge_ops, key_pack)
        states.append(state)
    if not states:
        raise DeviceUnsupported("empty streamed input")
    state, _cap = (_merge_states_host(states, 16, n_keys, nvals,
                                      merge_ops, key_pack)
                   if len(states) > 1 else (states[0], 0))
    out = jax.device_get(state[:5])
    key_out, key_null_out, results, result_nulls, n_groups = out
    ng = int(n_groups)
    if ng == 0 and not plan.group_exprs:
        raise DeviceUnsupported("empty global aggregate")
    return _assemble_agg(plan, key_meta, slots, dcols,
                         (key_out, key_null_out, results, result_nulls), ng)


def _stream_spec(col_arrays, batch_rows: int):
    """Arg-shape spec of one streamed block dispatch — (env, n_live)
    with every column padded to `batch_rows` — for the compile service's
    background warm (the env itself is built per block in the loop, so
    the shapes are described instead of materialized)."""
    import jax
    env_spec = {idx: (jax.ShapeDtypeStruct((batch_rows,),
                                           np.asarray(d).dtype),
                      jax.ShapeDtypeStruct((batch_rows,), np.bool_))
                for idx, (d, _nl) in col_arrays.items()}
    return (env_spec, jax.ShapeDtypeStruct((), np.int64))


#: partial-aggregate rows buffered on device before a merge flush (shared
#: by the streamed scan-agg and the paged probe join)
_MERGE_BUDGET_ROWS = 1 << 25


def _stream_count_distinct(plan, conds, chunk, col_arrays, dcols, cond_fns,
                           key_fns, key_meta, key_pack, val_plan, slots,
                           batch_rows, ctx):
    """Streamed COUNT(DISTINCT x): per-block dedup of (group, x) pairs,
    then one cnt_dist aggregate over the concatenated pair rows."""
    n = chunk.num_rows
    val_fn = val_plan[0][0]
    # block program: group keys + value as ONE key set, dedup via 'first'
    pair_fns = list(key_fns) + [val_fn]
    n_pair_keys = len(pair_fns)
    est = _estimate_groups(plan, n, ctx)
    # distinct pairs per block bounded by the block; estimate via group
    # est * a small per-group distinct factor, discovered on overflow
    capacity = dev.next_pow2(min(batch_rows, max(est * 4, 64)))
    n_blocks = (n + batch_rows - 1) // batch_rows
    sig_exprs, dict_refs = _agg_sig(plan, conds, dcols)
    for _attempt in range(8):
        if n_blocks * capacity > 4 * _MERGE_BUDGET_ROWS:
            # unlike the mergeable path this buffers EVERY block's pair
            # state — past the budget, degrade to the fallback instead of
            # exhausting device memory
            raise DeviceUnsupported(
                "distinct pair state exceeds the stream budget")
        key = (sig_exprs, "cntd", capacity)

        def build(cap=capacity):
            return _build_pipeline(cond_fns, pair_fns, n_pair_keys,
                                   [(val_fn, "int")], ("first",), cap,
                                   None)
        fn = acquire_pipeline(key, build, dict_refs, ctx=ctx,
                              spec=_stream_spec(col_arrays, batch_rows),
                              shape="agg", sig=sig_exprs, ladder=False)
        partials = []
        for lo in range(0, n, batch_rows):
            hi = min(lo + batch_rows, n)
            env = {idx: (jnp.asarray(dev.pad_host(d[lo:hi], batch_rows)),
                         jnp.asarray(dev.pad_host(nl[lo:hi], batch_rows,
                                                  True)))
                   for idx, (d, nl) in col_arrays.items()}
            partials.append(fn(env, np.int64(hi - lo)))
        counts = [int(c) for c in jax.device_get([p[4] for p in partials])]
        if max(counts) <= capacity:
            break
        capacity = dev.next_pow2(max(counts))
    else:
        raise DeviceUnsupported("distinct pair capacity did not converge")

    n_keys = max(len(key_fns), 1)
    # concatenated pair rows: group keys back apart from the value key
    if key_fns:
        key_cat = tuple(jnp.concatenate([p[0][k] for p in partials])
                        for k in range(n_keys))
        key_null_cat = tuple(jnp.concatenate([p[1][k] for p in partials])
                             for k in range(n_keys))
    else:
        # global COUNT(DISTINCT): one group — constant key, NOT the value
        tot = sum(int(p[0][0].shape[0]) for p in partials)
        key_cat = (jnp.zeros(tot, dtype=jnp.int64),)
        key_null_cat = (jnp.zeros(tot, dtype=bool),)
    val_cat = (jnp.concatenate([p[0][n_pair_keys - 1] for p in partials]),)
    val_null_cat = (jnp.concatenate([p[1][n_pair_keys - 1]
                                     for p in partials]),)
    mask = jnp.concatenate([jnp.arange(capacity) < p[4] for p in partials])
    total = int(mask.shape[0])
    final_cap = dev.next_pow2(max(est, 16))
    while True:
        out = jax.device_get(dev._agg_impl(
            key_cat, key_null_cat, val_cat, val_null_cat, mask,
            n_keys=n_keys, agg_ops=("cnt_dist",),
            capacity=min(final_cap, dev.next_pow2(total)), pack=key_pack))
        key_out, key_null_out, results, result_nulls, n_groups, _v = out
        ng = int(n_groups)
        if ng <= final_cap:
            break
        final_cap = dev.next_pow2(ng)
    if ng == 0 and not plan.group_exprs:
        raise DeviceUnsupported("empty global aggregate")
    return _assemble_agg(plan, key_meta, slots, dcols,
                         (key_out, key_null_out, results, result_nulls), ng)


def merge_partial_states(state, parts, merge_cap, n_keys, nvals, merge_ops,
                         key_pack):
    """Fold buffered partial-agg states (+ the running state) into ONE
    merged state of `merge_cap` output slots via the mergeable-agg kernel;
    grows merge_cap on overflow (inputs stay alive, so the retry is
    exact). Returns (state, merge_cap) — state is an _agg_impl output
    tuple whose [4] is the live group count.

    On the XLA-CPU backend with a packable key the fold runs in numpy
    instead: partial states are small and COMPACT (a few hundred k rows
    per flush), where the backend's serial sort and the dense-bucket
    scatter both pay in the key SPAN (measured: 13.5s of SF10 Q3's 45s
    device time was one 3.9M-row merge over a 67M-slot orderkey space);
    numpy's multiway argsort does the same fold in row-proportional
    time. On TPU the states stay in HBM and the sort kernel merges."""
    alls = ([state] if state is not None else []) + list(parts)
    if key_pack is not None and jax.default_backend() == "cpu":
        return _merge_states_host(alls, merge_cap, n_keys, nvals,
                                  merge_ops, key_pack)
    key_cat = tuple(jnp.concatenate([p[0][k] for p in alls])
                    for k in range(n_keys))
    key_null_cat = tuple(jnp.concatenate([p[1][k] for p in alls])
                         for k in range(n_keys))
    val_cat = tuple(jnp.concatenate([p[2][j] for p in alls])
                    for j in range(nvals))
    val_null_cat = tuple(jnp.concatenate([p[3][j] for p in alls])
                         for j in range(nvals))
    mask = jnp.concatenate([
        jnp.arange(p[0][0].shape[0]) < p[4] for p in alls])
    while True:
        out = dev._agg_impl(key_cat, key_null_cat, val_cat, val_null_cat,
                            mask, n_keys=n_keys, agg_ops=merge_ops,
                            capacity=merge_cap, pack=key_pack)
        ng = int(jax.device_get(out[4]))
        if ng <= merge_cap:
            return out, merge_cap
        merge_cap = dev.next_pow2(ng)


def page_singleton_state(key_cols, key_nulls, val_cols, val_nulls, mask,
                         agg_ops):
    """A raw fragment page (see compile_fragment raw_tail) viewed as a
    partial-agg state of SINGLETON groups, mergeable by
    _merge_states_host: a count op's singleton value is its 0/1 pre-count
    (its merge op is sum_i, and a count result is 0, never NULL); every
    other op's singleton value is the row's own value + null flag."""
    vals, vnulls = [], []
    for j, op in enumerate(agg_ops):
        v = np.asarray(val_cols[j])
        vn = np.asarray(val_nulls[j])
        if op == "count":
            vals.append((~vn).astype(np.int64))
            vnulls.append(np.zeros(vn.shape[0], dtype=bool))
        else:
            vals.append(v)
            vnulls.append(vn)
    m = np.asarray(mask)
    return (tuple(np.asarray(k) for k in key_cols),
            tuple(np.asarray(kn) for kn in key_nulls),
            tuple(vals), tuple(vnulls),
            int(np.count_nonzero(m)), m)


def _merge_states_host(alls, merge_cap, n_keys, nvals, merge_ops, key_pack):
    """numpy fold of partial-agg states (CPU backend only). Packs the key
    tuple EXACTLY like _agg_impl (null -> slot 0, value+offset+1), stable
    argsort so the first-occurrence row of every group is the earliest
    partial's representative (matching the kernel's stable-sort 'first'
    semantics), then reduceat per aggregate. Output layout mirrors an
    _agg_impl return: (keys, key_nulls, results, result_nulls, n_groups,
    valid)."""
    keys = [np.concatenate([np.asarray(p[0][k]) for p in alls])
            for k in range(n_keys)]
    knulls = [np.concatenate([np.asarray(p[1][k]) for p in alls])
              for k in range(n_keys)]
    vals = [np.concatenate([np.asarray(p[2][j]) for p in alls])
            for j in range(nvals)]
    vnulls = [np.concatenate([np.asarray(p[3][j]) for p in alls])
              for j in range(nvals)]
    # p[5] is each state's validity mask: arange<ng for compact kernel
    # states, an arbitrary row mask for raw singleton pages
    live = np.concatenate([np.asarray(p[5]) for p in alls])
    packed = np.zeros(live.shape[0], dtype=np.int64)
    for (bits, offset), k, kn in zip(key_pack, keys, knulls):
        shifted = k.astype(np.int64) + np.int64(offset + 1)
        packed = (packed << np.int64(bits)) | np.where(kn, 0, shifted)
    idx = np.nonzero(live)[0]
    order = np.argsort(packed[idx], kind="stable")
    sidx = idx[order]
    sk = packed[idx][order]
    m = sk.shape[0]
    new = np.empty(m, dtype=bool)
    if m:
        new[0] = True
        np.not_equal(sk[1:], sk[:-1], out=new[1:])
    bounds = np.nonzero(new)[0]
    ng = int(bounds.shape[0])
    cap = merge_cap
    while ng > cap:
        cap *= 2
    rep = sidx[bounds]

    def pad(a):
        out = np.zeros(cap, dtype=a.dtype)
        out[:ng] = a
        return out

    key_out = tuple(jnp.asarray(pad(k[rep])) for k in keys)
    key_null_out = tuple(jnp.asarray(pad(kn[rep])) for kn in knulls)
    results = []
    result_nulls = []
    for j, opn in enumerate(merge_ops):
        v = vals[j]
        vn = vnulls[j]
        if opn == "first":
            results.append(jnp.asarray(pad(v[rep])))
            result_nulls.append(jnp.asarray(pad(vn[rep])))
            continue
        svn = vn[sidx]
        nonnull = np.add.reduceat(
            (~svn).astype(np.int64), bounds) if ng else np.zeros(
                0, dtype=np.int64)
        if opn == "sum_i":
            sv = np.where(vn, 0, v.astype(np.int64))[sidx]
            seg = (np.add.reduceat(sv, bounds) if ng
                   else np.zeros(0, dtype=np.int64))
        elif opn == "sum_f":
            sv = np.where(vn, 0.0, v.astype(np.float64))[sidx]
            seg = (np.add.reduceat(sv, bounds) if ng
                   else np.zeros(0, dtype=np.float64))
        elif opn in ("min", "max"):
            if np.issubdtype(v.dtype, np.floating):
                sent = np.inf if opn == "min" else -np.inf
            else:
                ii = np.iinfo(v.dtype)
                sent = ii.max if opn == "min" else ii.min
            sv = np.where(vn, sent, v)[sidx]
            red = np.minimum if opn == "min" else np.maximum
            seg = (red.reduceat(sv, bounds) if ng
                   else np.zeros(0, dtype=v.dtype))
        else:
            raise ValueError(opn)
        results.append(jnp.asarray(pad(seg)))
        result_nulls.append(jnp.asarray(pad(nonnull == 0)
                                        if ng else np.zeros(cap, bool)))
    valid = jnp.arange(cap) < ng
    return (key_out, key_null_out, tuple(results), tuple(result_nulls),
            jnp.asarray(ng), valid), cap


#: window functions the device kernel computes (reference:
#: executor/window.go; unistore runs window fragments storage-side)
_WIN_RANKS = {"row_number", "rank", "dense_rank", "percent_rank",
              "cume_dist"}
_WIN_AGGS = {"sum", "count", "avg", "min", "max"}

def device_window(p, chunk: Chunk, ctx=None) -> Chunk:
    """Window functions as ONE jitted program: a single stable lexsort by
    (partition, order), then log-depth prefix scans for every function —
    no per-partition host loop (the host path iterates partitions in
    Python; reference executor/window.go processes them serially too).
    Default frames only: with ORDER BY, RANGE UNBOUNDED PRECEDING..CURRENT
    ROW (peer-aware); without, the whole partition. Raises
    DeviceUnsupported outside that language (ntile/lead/lag, explicit
    frames, distinct args) — the host executor covers the rest."""
    n = chunk.num_rows
    if n == 0:
        raise DeviceUnsupported("empty window input")
    for f in p.funcs:
        if f.frame is not None:
            raise DeviceUnsupported("explicit window frame")
        if f.name in _WIN_RANKS:
            continue
        if f.name not in _WIN_AGGS or len(f.args) != 1:
            raise DeviceUnsupported(f"window func {f.name}")
        if phys_kind(f.args[0].ftype) == K_STR and f.name not in ("count",):
            raise DeviceUnsupported("string window aggregate")

    used = set()
    for e in p.partition_exprs:
        e.columns_used(used)
    for e, _d in p.order_by:
        e.columns_used(used)
    for f in p.funcs:
        for a in f.args:
            a.columns_used(used)
    # bucketed upload: padding rows sort behind every live row (validity is
    # the most-major sort key) and form their own trailing partition, so no
    # rank/aggregate of a real partition ever sees them
    nb = dev.bucket_rows(n, dev.shape_buckets(ctx))
    dcols = {}
    env = {}
    for idx_ in used:
        dc = dev.to_device_col(chunk.columns[idx_], bucket=nb)
        dcols[idx_] = dc
        env[idx_] = (dc.data, dc.nulls)

    part_fns = [dev.compile_expr(e, dcols) for e in p.partition_exprs]
    order_fns = [(dev.compile_expr(e, dcols), d) for e, d in p.order_by]
    agg_fns = [dev.compile_expr(f.args[0], dcols)
               if f.name in _WIN_AGGS else None for f in p.funcs]
    has_order = bool(p.order_by)
    names = tuple(f.name for f in p.funcs)
    kinds = tuple(phys_kind(f.args[0].ftype) if f.name in _WIN_AGGS else None
                  for f in p.funcs)

    def run(env, n_live):
        _count_trace()
        # padded (bucket) length from the closure, NOT an env array: a
        # window over no columns at all (count(*) OVER ()) has an empty
        # env, and the cache key already pins nb
        n = nb
        i = jnp.arange(n)
        in_live = i < n_live
        lex = []  # minor → major: tiebreak, order keys reversed, partition

        def push_key(d, nl, desc):
            if jnp.issubdtype(d.dtype, jnp.floating):
                v = -d if desc else d
            else:
                v = d.astype(jnp.int64)
                if desc:
                    v = ~v
            lex.append(jnp.where(nl, 0, v))
            # MySQL: NULLs first ASC, last DESC
            lex.append(jnp.where(nl, 1 if desc else 0, 0 if desc else 1))

        order_kvs = []
        for fn, desc in order_fns:
            d, nl = dev.broadcast_1d(*fn(env), n)
            order_kvs.append((d, nl))
        part_kvs = []
        for fn in part_fns:
            d, nl = dev.broadcast_1d(*fn(env), n)
            part_kvs.append((d, nl))
        for (d, nl), (_f, desc) in zip(reversed(order_kvs),
                                       reversed(order_fns)):
            push_key(d, nl, desc)
        for d, nl in reversed(part_kvs):
            push_key(d, nl, False)
        # validity is the MOST-major key: bucket-padding rows sort behind
        # every live row (stable, so a keyless window keeps input order)
        lex.append(~in_live)
        idx = jnp.lexsort(lex)
        inv = jnp.argsort(idx)

        def change(kvs):
            ch = jnp.zeros(n, dtype=bool).at[0].set(True)
            for d, nl in kvs:
                # NULL rows carry arbitrary raw data (_agg_impl invariant,
                # ops/device.py): value-mask before comparing, or NULL runs
                # split on garbage and every rank/agg restarts mid-group
                dm = jnp.where(nl, jnp.zeros((), dtype=d.dtype), d)
                ds, ns = dm[idx], nl[idx]
                delta = jnp.concatenate([
                    jnp.ones(1, dtype=bool),
                    (ds[1:] != ds[:-1]) | (ns[1:] != ns[:-1])])
                ch = ch | delta
            return ch

        part_change = (change(part_kvs) if part_kvs
                       else jnp.zeros(n, dtype=bool).at[0].set(True))
        # sorted position n_live is the first padding row (validity-major
        # sort): force a partition boundary there so padding forms its own
        # trailing segment and never extends a real partition's frame
        part_change = part_change | (i == n_live)
        peer_change = part_change | (change(order_kvs) if order_kvs
                                     else jnp.zeros(n, dtype=bool))
        spos = jax.lax.cummax(jnp.where(part_change, i, -1))
        ppos = jax.lax.cummax(jnp.where(peer_change, i, -1))

        def seg_end(chg):
            # smallest later index starting a new segment, minus one
            nxt = jnp.concatenate([
                jnp.where(chg[1:], i[1:], n), jnp.full(1, n)])
            fut = jnp.flip(jax.lax.cummin(jnp.flip(nxt)))
            return fut - 1

        epos = seg_end(part_change)
        pe = seg_end(peer_change) if has_order else epos
        m = epos - spos + 1

        outs = []
        for name, fn, k in zip(names, agg_fns, kinds):
            if name == "row_number":
                outs.append(((i - spos + 1)[inv], jnp.zeros(n, dtype=bool)))
                continue
            if name == "rank":
                outs.append(((ppos - spos + 1)[inv],
                             jnp.zeros(n, dtype=bool)))
                continue
            if name == "dense_rank":
                c = jnp.cumsum(peer_change)
                outs.append(((c - c[spos] + 1)[inv],
                             jnp.zeros(n, dtype=bool)))
                continue
            if name == "percent_rank":
                r = (ppos - spos).astype(jnp.float64)
                outs.append((jnp.where(m > 1, r / jnp.maximum(m - 1, 1),
                                       0.0)[inv],
                             jnp.zeros(n, dtype=bool)))
                continue
            if name == "cume_dist":
                v = (pe - spos + 1).astype(jnp.float64) / m
                outs.append((v[inv], jnp.zeros(n, dtype=bool)))
                continue
            d, nl = dev.broadcast_1d(*fn(env), n)
            ds, ns = d[idx], nl[idx]
            end = pe  # default frame: through the current peer group
            cnt_v = (~ns).astype(jnp.int64)
            ccs = jnp.cumsum(cnt_v)
            cnt_run = ccs[end] - ccs[spos] + cnt_v[spos]
            if name == "count":
                outs.append((cnt_run[inv], jnp.zeros(n, dtype=bool)))
                continue
            if name in ("sum", "avg"):
                if k == K_FLOAT:
                    # segmented scan, NOT prefix-sum differences: the
                    # global cumsum carries earlier partitions' magnitude
                    # into this partition's rounding error (same invariant
                    # as the agg kernel, ops/device.py _agg_impl)
                    z = jnp.where(ns, 0.0, ds)
                    s = dev._seg_running(jnp.add, part_change, z)[end]
                else:
                    z = jnp.where(ns, 0, ds)
                    cs = jnp.cumsum(z)  # ints: differences are exact
                    s = cs[end] - cs[spos] + z[spos]
                outs.append((s[inv], (cnt_run == 0)[inv]))
                if name == "avg":  # host assembly divides sum by count
                    outs.append((cnt_run[inv], jnp.zeros(n, dtype=bool)))
                continue
            # min / max: flagged segmented running scan, read at `end`;
            # the null identity must match the column's DEVICE dtype —
            # int64 extremes silently wrap on int32-backed DATE columns
            if k == K_FLOAT:
                ident = jnp.inf if name == "min" else -jnp.inf
            else:
                info = jnp.iinfo(ds.dtype)
                ident = info.max if name == "min" else info.min
            z = jnp.where(ns, ident, ds)
            comb = jnp.minimum if name == "min" else jnp.maximum
            scan = dev._seg_running(comb, part_change, z)
            v = scan[end]
            outs.append((v[inv], (cnt_run == 0)[inv]))
        return tuple(outs)

    # dictionary CONTENT is the load-bearing key component: compiled
    # str-expr LUTs bake the dictionary's codes, exactly like the agg
    # pipeline cache (_agg_sig / _pipe_cache_put); the shape key is the
    # BUCKET, so a within-bucket delta re-dispatches the compiled program
    dict_refs = tuple(dc.dictionary for dc in dcols.values()
                      if dc.dictionary is not None)
    sig = (nb, names, kinds, has_order,
           tuple(_expr_sig(e) for e in p.partition_exprs),
           tuple((_expr_sig(e), d) for e, d in p.order_by),
           tuple(_expr_sig(f.args[0]) if f.name in _WIN_AGGS else None
                 for f in p.funcs),
           tuple(f"{idx_}:{_dc_sig(dc)}" for idx_, dc in sorted(dcols.items())
                 if dc.dictionary is not None))
    fn = acquire_pipeline(("win",) + sig, lambda: _timed_jit(run),
                          dict_refs, ctx=ctx, args=(env, np.int64(n)),
                          shape="window", sig=sig)
    outs = jax.device_get(fn(env, np.int64(n)))

    # outputs are padded to the bucket; positions past the live rows belong
    # to the trailing padding partition — slice them away
    outs = tuple((np.asarray(d)[:n], np.asarray(nl)[:n]) for d, nl in outs)
    out_cols = list(chunk.columns)
    oi = 0
    for f in p.funcs:
        ft = f.ftype
        if f.name == "avg":
            s = np.asarray(outs[oi][0])
            s_null = np.asarray(outs[oi][1])
            c = np.asarray(outs[oi + 1][0])
            oi += 2
            arg = f.args[0]
            if phys_kind(ft) == K_FLOAT:
                vals = s / np.maximum(c, 1)
                if phys_kind(arg.ftype) == K_DEC:
                    # decimal args evaluate as scaled ints — unscale for
                    # the double-typed window AVG
                    vals = vals / POW10[arg.ftype.scale]
                out_cols.append(Column(ft, vals, s_null))
            else:
                s_arg = (arg.ftype.scale
                         if phys_kind(arg.ftype) == K_DEC else 0)
                shift = POW10[ft.scale - s_arg]
                num = s.astype(object) * shift
                den = np.maximum(c, 1).astype(object)
                sign = np.where(num < 0, -1, 1)
                q = (2 * np.abs(num) + den) // (2 * den)
                vals = np.array([int(x) for x in sign * q], dtype=np.int64)
                out_cols.append(Column(ft, vals, s_null))
            continue
        vals, nulls = outs[oi]
        oi += 1
        vals = np.asarray(vals)
        nulls = np.asarray(nulls)
        dt = np_dtype_for(ft)
        if dt is not object and vals.dtype != dt:
            vals = vals.astype(dt)
        out_cols.append(Column(ft, vals, nulls))
    return Chunk(out_cols)


def device_join_keys(lkeys, rkeys):
    """Combine multi-column join keys into single int64 codes host-side
    (shared factorization), then match on device. Returns (li, ri).

    Single raw-int64 keys skip the factorization pass entirely — the
    device matcher is sort-based and handles arbitrary int64 values
    (null rows are masked by the kernel / the keep filter)."""
    if (len(lkeys) == 1 and lkeys[0][0].dtype == np.int64
            and rkeys[0][0].dtype == np.int64):
        (pd, pn), = lkeys
        (bd, bn), = rkeys
        return dev.device_join_match((bd, bn), (pd, pn))
    nb = len(rkeys[0][0])
    npr = len(lkeys[0][0])
    from ..ops import host as hops
    acc_b = np.zeros(nb, dtype=np.int64)
    acc_p = np.zeros(npr, dtype=np.int64)
    b_null = np.zeros(nb, dtype=bool)
    p_null = np.zeros(npr, dtype=bool)
    for (pd, pn), (bd, bn) in zip(lkeys, rkeys):
        both = np.concatenate([bd, pd])
        codes, card = hops.factorize_column(both, np.concatenate([bn, pn]))
        acc_b = acc_b * np.int64(card + 1) + (codes[:nb] + 1)
        acc_p = acc_p * np.int64(card + 1) + (codes[nb:] + 1)
        b_null |= bn
        p_null |= pn
    return dev.device_join_match((acc_b, b_null), (acc_p, p_null))
