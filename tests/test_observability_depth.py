"""Observability depth (VERDICT round-2 missing #9): optimizer trace,
plan replayer, TopSQL, metrics_schema / performance_schema (reference:
planner/core/optimizer.go:93-126, executor/plan_replayer.go,
util/topsql/topsql.go:54, infoschema/metrics_schema.go, perfschema/)."""

import json
import zipfile

import pytest

from tidb_tpu.testkit import TestKit


@pytest.fixture()
def tk():
    tk = TestKit()
    tk.must_exec("use test")
    tk.must_exec("create table t (id int primary key, a int, b int, "
                 "key ia (a))")
    tk.must_exec("insert into t values "
                 + ",".join(f"({i},{i % 10},{i % 3})" for i in range(200)))
    tk.must_exec("analyze table t")
    return tk


class TestOptimizerTrace:
    def test_rule_steps_present(self, tk):
        r = tk.must_query(
            "trace format='opt' select b, count(*) from t "
            "where a = 3 and id > 10 group by b")
        rules = {row[1] for row in r.rows}
        for rule in ("initial", "predicate_push_down", "column_pruning",
                     "access_path_selection"):
            assert rule in rules, rules
        assert r.result.names == ["step", "rule", "plan"]

    def test_trace_shows_plan_evolution(self, tk):
        r = tk.must_query(
            "trace format='opt' select * from t where a = 3")
        txt = {rule: [] for _s, rule, _l in r.rows}
        for _s, rule, line in r.rows:
            txt[rule].append(line)
        # the access-path rule turns the scan into an index lookup
        assert any("IndexLookUp" in l or "index:ia" in l
                   for l in txt["access_path_selection"])
        assert not any("IndexLookUp" in l for l in txt["initial"])

    def test_plain_trace_still_works(self, tk):
        r = tk.must_query("trace select count(*) from t")
        assert any("executor.run" in row[0] for row in r.rows)


class TestPlanReplayer:
    def test_dump_zip_contents(self, tk):
        r = tk.must_query(
            "plan replayer dump explain select a, count(*) from t "
            "where b = 1 group by a")
        path = r.rows[0][0]
        assert path.endswith(".zip")
        with zipfile.ZipFile(path) as z:
            names = set(z.namelist())
            assert {"sql/sql_meta.toml", "schema/schema.sql",
                    "stats/stats.json", "variables.json",
                    "explain.txt"} <= names
            schema = z.read("schema/schema.sql").decode()
            assert "CREATE TABLE" in schema and "`t`" in schema
            stats = json.loads(z.read("stats/stats.json"))
            assert "test.t" in stats and stats["test.t"]["row_count"] == 200
            assert "HashAgg" in z.read("explain.txt").decode()

    def test_restore_parses(self, tk):
        from tidb_tpu.parser import parse
        s = parse("plan replayer dump explain select * from t")[0]
        assert "PLAN REPLAYER DUMP EXPLAIN" in s.restore()
        parse(s.restore())  # round-trips


class TestTopSQL:
    def test_sampling_attributes_cpu(self, tk):
        tk.must_exec("set global tidb_enable_top_sql = ON")
        sess = tk.session
        sess.current_sql = "select heavy from t"
        try:
            for _ in range(5):
                tk.domain.topsql.sample_once()
        finally:
            sess.current_sql = None
        rows = tk.must_query(
            "select sample_sql, cpu_time_ms, samples from "
            "information_schema.tidb_top_sql").rows
        assert any("heavy" in r[0] and int(r[2]) == 5 for r in rows)

    def test_disabled_by_default(self, tk):
        sess = tk.session
        sess.current_sql = "select idle from t"
        try:
            tk.domain.topsql.sample_once()
        finally:
            sess.current_sql = None
        rows = tk.must_query(
            "select * from information_schema.tidb_top_sql").rows
        assert not any("idle" in str(r) for r in rows)


class TestSchemas:
    def test_performance_schema_digest_summary(self, tk):
        tk.must_query("select count(*) from t")
        tk.must_exec("use performance_schema")
        rows = tk.must_query(
            "select digest_text, count_star, sum_timer_wait from "
            "events_statements_summary_by_digest "
            "where digest_text like '%COUNT(*)%'").rows
        assert rows and int(rows[0][1]) >= 1
        assert int(rows[0][2]) > 0  # picoseconds

    def test_metrics_schema_summary(self, tk):
        tk.must_query("select 1 from t limit 1")
        tk.must_exec("use metrics_schema")
        rows = tk.must_query(
            "select sum_value from metrics_summary where "
            "metrics_name = 'executor_statement_total'").rows
        assert rows and float(rows[0][0]) >= 1

    def test_metrics_tables_listing(self, tk):
        rows = tk.must_query(
            "select table_name from information_schema.metrics_tables").rows
        assert any("executor_statement_total" in r[0] for r in rows)
