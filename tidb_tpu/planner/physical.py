"""Physical join-algorithm selection (reference:
planner/core/exhaust_physical_plans.go:1774 — hash/merge/index-lookup join
alternatives per logical Join — and find_best_task.go:359 cost choice).

The task model here is the host↔TPU split: every algorithm produces the
same matched row set, so the chooser is free to pick by cost alone.

  * IndexJoin  — the outer (left) side drives point lookups on the inner
    table's index or handle, skipping the inner full scan entirely.
    Wins when est(outer) rows of seeks cost less than scanning the inner
    table (reference: executor/index_lookup_join.go).
  * MergeJoin  — single primitive-typed equi-key: argsort both key arrays
    directly and merge with searchsorted, skipping the dictionary
    factorization pass the hash matcher needs for arbitrary/composite
    keys (reference: executor/merge_join.go exploits sort order; here
    the "order" is produced in-kernel, so it applies to any large
    primitive join).
  * HashJoin   — the default; composite or string keys, or small inputs
    where the factorize pass is noise.
"""

from __future__ import annotations

from ..expression.core import Column, K_DEC, K_FLOAT, K_INT, phys_kind
from ..model import SchemaState
from .access import SCAN_ROW_COST, SEEK_BASE, SEEK_COST
from .logical import DataSource, Join
from .optimizer import _est_rows

#: below this many estimated rows on both sides, factorization cost is
#: noise and hash join keeps the simplest plan
MERGE_MIN_ROWS = 4096
#: per-build-row hash table constant (insert + key factorization) and
#: per-comparison constant of the in-kernel merge sort, in the same
#: per-row units as access.py's SCAN_ROW_COST/SEEK_COST so access and
#: join decisions share one cost currency
HASH_BUILD_COST = 2.0
MERGE_SORT_COST = 0.05
#: never index-join when the outer side is estimated bigger than this
#: fraction of the inner table (seeks would exceed the scan)
INDEX_JOIN_MAX_KEYS = 65536


def choose_join_algos(plan, ctx, hints=None):
    if isinstance(plan, Join):
        _choose(plan, ctx, hints)
    for c in plan.children:
        choose_join_algos(c, ctx, hints)
    return plan


_HINT_ALGO = {"hash_join": "hash", "merge_join": "merge",
              "inl_join": "index", "index_join": "index"}


def _ds_direct(plan) -> set:
    """Lowercased name + alias when this child IS a table scan (looking
    through filters/projections but NOT into nested joins): a join hint
    only applies to the join the named table directly participates in
    (reference: hints bind to their query block's join, not ancestors)."""
    from .logical import Projection, Selection
    p = plan
    while isinstance(p, (Selection, Projection)):
        p = p.children[0]
    out = set()
    if isinstance(p, DataSource):
        out.add(p.table_info.name.lower())
        if p.alias:
            out.add(p.alias.lower())
    return out


def _hint_algo(join, hints):
    """First join-algorithm hint naming a DIRECT child table of this join
    wins (reference: planner/core/exhaust_physical_plans.go honors
    HASH_JOIN/MERGE_JOIN/INL_JOIN before cost). Returns (algo, matched
    names on right side, matched on left) or None."""
    if not hints:
        return None
    left_names = right_names = None
    for name, args in hints:
        algo = _HINT_ALGO.get(name)
        if algo is None:
            continue
        if left_names is None:
            left_names = _ds_direct(join.left)
            right_names = _ds_direct(join.right)
        wanted = {a.split("[", 1)[0] for a in args}
        mr = wanted & right_names
        ml = wanted & left_names
        if mr or ml:
            return algo, mr, ml
    return None


def _primitive(ft) -> bool:
    return phys_kind(ft) in (K_INT, K_FLOAT, K_DEC)


def _inner_index(join):
    """Index-join applicability: the inner (right) side is a plain
    DataSource scan and the single right key is a bare column that is the
    row handle or the first column of a public index."""
    ds = join.right
    if not isinstance(ds, DataSource) or ds.access is not None:
        return None
    if ds.table_info.partition is not None:
        return None
    if len(join.right_keys) != 1 or not isinstance(join.right_keys[0],
                                                   Column):
        return None
    # seeks reuse the raw outer key values: both sides must be plain ints
    # (a decimal/float/collated outer key would encode a different seek key
    # than the index stores)
    if (phys_kind(join.right_keys[0].ftype) != K_INT
            or phys_kind(join.left_keys[0].ftype) != K_INT):
        return None
    rcol = join.right_keys[0]
    if rcol.idx >= len(ds.col_infos):
        return None
    ci = ds.col_infos[rcol.idx]
    info = ds.table_info
    if info.pk_is_handle and ci.id == info.pk_col_id:
        return ("pk",)
    # honor USE/FORCE/IGNORE INDEX on the inner table, same contract as
    # the access-path chooser
    from .access import _hint_sets, _idx_allowed
    allowed, excluded, _forced = _hint_sets(ds)
    best = None
    for idx in info.indexes:
        if idx.state != SchemaState.PUBLIC or not idx.columns:
            continue
        if not _idx_allowed(idx, allowed, excluded):
            continue
        if idx.columns[0].name != ci.name:
            continue
        if idx.unique and len(idx.columns) == 1:
            return ("index", idx)  # unique single-col: 1 seek per key
        best = best or ("index", idx)
    return best


def _choose(join: Join, ctx, hints=None):
    join.join_algo = "hash"
    join.index_join = None
    if not join.left_keys or join.kind not in ("inner", "left", "semi",
                                               "anti"):
        return
    hit = _hint_algo(join, hints)
    if hit is not None:
        forced, matched_right, _matched_left = hit
        if forced == "hash":
            return
        if forced == "merge":
            # executor constraint: the merge matcher needs one primitive
            # key; an ineligible hint degrades to hash rather than
            # erroring (reference: a non-applicable hint warns, drops)
            if (len(join.left_keys) == 1
                    and _primitive(join.left_keys[0].ftype)
                    and _primitive(join.right_keys[0].ftype)):
                join.join_algo = "merge"
            return
        if forced == "index":
            # INL_JOIN(t) makes t the lookup (inner) side; that side is
            # structurally the right child here, so a hint naming only
            # the left table degrades like other non-applicable hints
            # (reference warns and drops them too) — forcing it on the
            # wrong side would invert the hint's meaning
            if matched_right:
                desc = _inner_index(join)
                if desc is not None:
                    join.join_algo = "index"
                    join.index_join = desc
            return
    outer_est = _est_rows(join.left, ctx)
    inner_est = _est_rows(join.right, ctx)

    # ---- explicit variant enumeration (reference: every eligible
    # physical join is costed and the cheapest wins —
    # exhaust_physical_plans.go:1774 emits the candidates,
    # find_best_task.go:359 compares task costs). Costs are in the same
    # per-row units the access-path chooser uses, so seek-vs-scan and
    # join-variant decisions share one currency.
    #   hash : build a table over the inner rows, probe with the outer —
    #          both sides pass once, plus a per-build-row table constant
    #   merge: order both sides (the in-kernel sort the merge matcher
    #          runs) — n·log n on each side, cheap constants
    #   index: one KV seek per outer row instead of reading the inner
    #          side at all — wins only under selective outer estimates
    candidates = {"hash": (outer_est + inner_est) * SCAN_ROW_COST
                  + inner_est * HASH_BUILD_COST}
    if (len(join.left_keys) == 1
            and _primitive(join.left_keys[0].ftype)
            and _primitive(join.right_keys[0].ftype)
            and min(outer_est, inner_est) >= MERGE_MIN_ROWS):
        import math
        candidates["merge"] = MERGE_SORT_COST * (
            outer_est * math.log2(max(outer_est, 2))
            + inner_est * math.log2(max(inner_est, 2)))
    desc = _inner_index(join)
    if desc is not None and outer_est <= INDEX_JOIN_MAX_KEYS:
        # the index join still reads the outer side once; seeks replace
        # the inner-side read entirely. Every variant prices the inner
        # side from the SAME post-filter estimate — re-costing hash from
        # raw table rows here would flip plans on index existence rather
        # than on cost
        candidates["index"] = (outer_est * SCAN_ROW_COST
                               + SEEK_BASE + outer_est * SEEK_COST)
    join.join_algo = min(candidates, key=candidates.get)
    join.join_cost = round(candidates[join.join_algo], 1)
    join.cost_candidates = {k: round(v, 1) for k, v in candidates.items()}
    if join.join_algo == "index":
        join.index_join = desc
