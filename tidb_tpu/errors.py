"""Centralized error classes with MySQL error codes.

Mirrors the role of the reference's ``errno/`` + ``util/dbterror``
(reference: errno/errcode.go, util/dbterror/terror.go): every user-visible
error carries a MySQL errno + SQL state so the protocol layer and tests can
match on codes, not strings.
"""


class ErrCode:
    # Subset of MySQL error codes used across the engine (reference: errno/errcode.go).
    DupEntry = 1062
    NoSuchTable = 1146
    PluginIsNotLoaded = 1524
    BadDB = 1049
    DBCreateExists = 1007
    DBDropExists = 1008
    TableExists = 1050
    BadTable = 1051
    BadField = 1054
    NonUniq = 1052
    ParseError = 1064
    UnknownSystemVariable = 1193
    WrongValueCountOnRow = 1136
    BadNull = 1048
    NoDefaultValue = 1364
    DataTooLong = 1406
    DataOutOfRange = 1264
    TruncatedWrongValue = 1292
    DivisionByZero = 1365
    LockWaitTimeout = 1205
    DeadlockDetected = 1213
    WrongFieldSpec = 1063
    DupKeyName = 1061
    KeyDoesNotExist = 1176
    CantDropFieldOrKey = 1091
    UnknownTable = 1109
    NoPermission = 1142
    TableaccessDenied = 1142
    DBaccessDenied = 1044
    AccessDenied = 1045
    CannotUser = 1396
    WrongDBName = 1102
    WrongTableName = 1103
    WrongColumnName = 1166
    InvalidGroupFuncUse = 1111
    MixOfGroupFuncAndFields = 1140
    FieldNotInGroupBy = 1055
    UnknownColumn = 1054
    OperandColumns = 1241
    SubqueryMoreThan1Row = 1242
    WrongNumberOfColumnsInSelect = 1222
    CantReopenTable = 1137
    WrongAutoKey = 1075
    MultiplePriKey = 1068
    TooManyKeys = 1069
    UnsupportedDDL = 8214
    PlacementPolicyExists = 8238
    PlacementPolicyNotExists = 8239
    CantExecuteInReadOnlyTxn = 1792
    AsOfInTxn = 8135
    InfoSchemaExpired = 8027
    InfoSchemaChanged = 8028
    WriteConflict = 9007
    TxnRetryable = 8002
    TiKVServerTimeout = 9002
    BackoffExhausted = 9005  # reference: ErrRegionUnavailable family —
    #                          the budgeted Backoffer ran out of retries
    DeviceHang = 9008  # reserved next to 9005: a supervised device call
    #                    blew its wall-clock deadline (the backend hung)
    DeviceAdmission = 9009  # the serving scheduler refused a fragment a
    #                         device slot (queue full / wait timed out)
    DeviceCompile = 9010  # the compile service could not build a device
    #                       executable (remote-compile RPC/transport
    #                       failure, injected compile fault, retry budget
    #                       exhausted) — the fragment degrades to host
    FreshnessWaitTimeout = 9011  # a snapshot's fleet-frontier wait blew
    #                              its budget: the read is REFUSED loudly
    #                              (never silently served stale), and the
    #                              lagging origin's freshness breaker
    #                              trips so one wedged worker cannot
    #                              freeze fleet reads (kv/shared_store)
    LazyUniquenessCheckFailure = 8147
    ResolveLockTimeout = 9004
    GCTooEarly = 9006
    UnsupportedType = 8003
    QueryInterrupted = 1317
    NoSuchThread = 1094
    MemExceedThreshold = 8001
    OOMKill = 8175
    # partitioned tables (MySQL partition error numbers)
    PartitionsMustBeDefined = 1492
    RangeNotIncreasing = 1493
    SameNamePartition = 1517
    DropLastPartition = 1508
    DropPartitionNonExistent = 1507
    NoPartitionForGivenValue = 1526
    PartitionMgmtOnNonpartitioned = 1505
    UniqueKeyNeedAllFieldsInPf = 1503
    PartitionRequiresValues = 1479
    WrongObject = 1347
    ViewRecursive = 1462
    ViewInvalid = 1356
    NonInsertableTable = 1471
    NonUpdatableTable = 1288
    DupFieldName = 1060
    SequenceRunOut = 4135
    WrongObjectSequence = 1347
    TableLocked = 8020
    TableNotLocked = 1100
    TableNotLockedForWrite = 1099
    OptOnCacheTable = 8242
    RowDoesNotMatchPartition = 1737
    PartitionFunctionIsNotAllowed = 1564
    UnknownPartition = 1735
    OnlyOnRangeListPartition = 1512


class TiDBError(Exception):
    """Base error: carries MySQL errno + sqlstate for the wire protocol."""

    code = 1105  # ER_UNKNOWN_ERROR
    sqlstate = "HY000"

    def __init__(self, msg="", code=None):
        super().__init__(msg)
        if code is not None:
            self.code = code
        self.msg = msg

    def __str__(self):
        return self.msg or self.__class__.__name__


class ParseError(TiDBError):
    code = ErrCode.ParseError
    sqlstate = "42000"


class SchemaError(TiDBError):
    code = ErrCode.NoSuchTable
    sqlstate = "42S02"


class ColumnError(TiDBError):
    code = ErrCode.BadField
    sqlstate = "42S22"


class DupEntryError(TiDBError):
    code = ErrCode.DupEntry
    sqlstate = "23000"


class WriteConflictError(TiDBError):
    code = ErrCode.WriteConflict
    sqlstate = "HY000"


class SchemaChangedError(TiDBError):
    """The schema a transaction's mutations were built against changed
    before commit (reference: domain.ErrInfoSchemaChanged, 8028 — the
    commit-time schema check that upholds the F1 online-DDL invariant)."""

    code = ErrCode.InfoSchemaChanged
    sqlstate = "HY000"


class LockedError(TiDBError):
    """Key is locked by another transaction (reference: kv lock errors)."""

    code = ErrCode.LockWaitTimeout
    sqlstate = "HY000"

    def __init__(self, msg="", key=None, lock_ts=0):
        super().__init__(msg)
        self.key = key
        self.lock_ts = lock_ts


class DeadlockError(TiDBError):
    code = ErrCode.DeadlockDetected
    sqlstate = "40001"


class TypeError_(TiDBError):
    code = ErrCode.TruncatedWrongValue
    sqlstate = "22007"


class OutOfRangeError(TiDBError):
    code = ErrCode.DataOutOfRange
    sqlstate = "22003"


class PrivilegeError(TiDBError):
    code = ErrCode.NoPermission
    sqlstate = "42000"


class QueryInterruptedError(TiDBError):
    code = ErrCode.QueryInterrupted
    sqlstate = "70100"


class MemoryQuotaExceeded(TiDBError):
    code = ErrCode.MemExceedThreshold
    sqlstate = "HY000"


class DeviceHangError(TiDBError):
    """A supervised device call exceeded its hard wall-clock deadline
    (`tidb_device_call_timeout` / the remaining `max_execution_time`
    window): the backend is presumed hung inside a GIL-holding C call the
    engine cannot interrupt.  The call is ABANDONED on its worker thread,
    the JAX backend is fenced (compiled-executable caches quarantined and
    reinitialized before the next fragment), and the hang is recorded
    against the per-shape circuit breaker so repeated hangs degrade the
    fragment class to the host engine.

    `shape` names the fragment class that hung (agg / join / window /
    mpp), `deadline_s` the budget that expired."""

    code = ErrCode.DeviceHang
    sqlstate = "HY000"
    shape = ""
    deadline_s = 0.0


class DeviceAdmissionError(TiDBError):
    """The serving scheduler (executor/scheduler.py) refused this
    fragment a device slot: the admission queue is at
    ``tidb_device_sched_queue_depth``, the queued wait exceeded
    ``tidb_device_admission_timeout``, or an admission failpoint fired.

    This is LOAD, not ill-health: run_device converts the refusal into
    ``DeviceUnsupported`` so the fragment degrades to the host engine
    (counted in the per-tenant ``sched_degradations`` gauge) without
    charging the circuit breaker — the co-processing answer to overload
    is host+device serving different work concurrently, not an error."""

    code = ErrCode.DeviceAdmission
    sqlstate = "HY000"


class DeviceCompileError(TiDBError):
    """The compile service (executor/compile_service.py) failed to build a
    device executable for a fragment signature: the remote-compile
    RPC/transport died mid-compile, an injected ``compile-fail`` failpoint
    fired, or the ``compileRetry`` backoff budget ran out.

    This is a COMPILE-path failure, not an execution failure: it charges
    the compile-scoped circuit breaker (shape="compile") — never the
    fragment-shape breakers — and the fragment degrades to the host
    engine (the executable may still land on a later attempt, flipping
    subsequent executions back to device)."""

    code = ErrCode.DeviceCompile
    sqlstate = "HY000"


class FreshnessWaitError(TiDBError):
    """A snapshot's fleet-frontier wait (kv/shared_store.fresh_read_ts)
    exhausted its ``freshnessWait`` budget: some live origin published a
    durable commit frontier this replica could not apply up to in time.

    This is the LOUD stale-read refusal of the consistency ladder — the
    engine never silently serves a snapshot older than the fleet
    frontier.  The lagging origin's per-origin freshness breaker trips
    with the raise, so subsequent reads degrade to an explicit
    ``stale_ok`` downgrade (surfaced in EXPLAIN ANALYZE and the
    ``freshness_stale_ok`` gauge) instead of re-paying the budget."""

    code = ErrCode.FreshnessWaitTimeout
    sqlstate = "HY000"


class BackoffExhaustedError(TiDBError):
    """A budgeted retry loop ran out of budget (reference: client-go
    "backoffer.maxSleep exceeded" — surfaced as a region-unavailable
    class timeout, never an unbounded loop).

    Carries `retry_kind` (which curve exhausted) and `error_class` (the
    taxonomy label of the last triggering error, utils/backoff.classify)."""

    code = ErrCode.BackoffExhausted
    sqlstate = "HY000"
    retry_kind = ""
    error_class = ""
