"""Schema model objects shared by catalog, DDL, planner
(reference: parser/model/model.go — DBInfo/TableInfo/ColumnInfo/IndexInfo/Job
and the F1 schema states)."""

from __future__ import annotations

import json
from dataclasses import dataclass, field

from .sqltypes import FieldType


class SchemaState:
    """F1 online-schema-change states (reference: parser/model/model.go:33)."""
    NONE = 0
    DELETE_ONLY = 1
    WRITE_ONLY = 2
    WRITE_REORG = 3
    PUBLIC = 4
    DELETE_REORG = 5

    NAMES = {0: "none", 1: "delete only", 2: "write only",
             3: "write reorganization", 4: "public", 5: "delete reorganization"}


@dataclass
class ColumnInfo:
    id: int = 0
    name: str = ""
    offset: int = 0
    ftype: FieldType = None
    state: int = SchemaState.PUBLIC
    default_value: object = None  # internal-representation value or None
    has_default: bool = False
    comment: str = ""
    hidden: bool = False

    def to_json(self):
        ft = self.ftype
        return {
            "id": self.id, "name": self.name, "offset": self.offset,
            "tp": ft.tp, "flen": ft.flen, "decimal": ft.decimal,
            "flag": ft.flag, "charset": ft.charset, "collate": ft.collate,
            "elems": list(ft.elems),
            "state": self.state, "default": _enc(self.default_value),
            "has_default": self.has_default, "comment": self.comment,
            "hidden": self.hidden,
        }

    @classmethod
    def from_json(cls, d):
        return cls(
            id=d["id"], name=d["name"], offset=d["offset"],
            ftype=FieldType(tp=d["tp"], flen=d["flen"], decimal=d["decimal"],
                            flag=d["flag"], charset=d["charset"],
                            collate=d["collate"], elems=tuple(d["elems"])),
            state=d["state"], default_value=_dec(d["default"]),
            has_default=d["has_default"], comment=d.get("comment", ""),
            hidden=d.get("hidden", False),
        )


@dataclass
class IndexColumn:
    name: str = ""
    offset: int = 0
    length: int = -1  # prefix length or -1


@dataclass
class IndexInfo:
    id: int = 0
    name: str = ""
    columns: list = field(default_factory=list)  # [IndexColumn]
    unique: bool = False
    primary: bool = False
    state: int = SchemaState.PUBLIC

    def to_json(self):
        return {"id": self.id, "name": self.name, "unique": self.unique,
                "primary": self.primary, "state": self.state,
                "columns": [{"name": c.name, "offset": c.offset, "length": c.length}
                            for c in self.columns]}

    @classmethod
    def from_json(cls, d):
        return cls(id=d["id"], name=d["name"], unique=d["unique"],
                   primary=d["primary"], state=d["state"],
                   columns=[IndexColumn(c["name"], c["offset"], c["length"])
                            for c in d["columns"]])


@dataclass
class PartitionDef:
    """One physical partition (reference: parser/model/model.go
    PartitionDefinition). `id` is the partition's physical table id — row and
    index keys for rows routed here use this id, not the logical table's."""
    id: int = 0
    name: str = ""
    less_than: object = None     # RANGE: upper bound value or "MAXVALUE"
    in_values: list = None       # LIST: accepted values (None encodes NULL)

    def to_json(self):
        return {"id": self.id, "name": self.name,
                "less_than": _enc(self.less_than),
                "in_values": (None if self.in_values is None
                              else [_enc(v) for v in self.in_values])}

    @classmethod
    def from_json(cls, d):
        return cls(id=d["id"], name=d["name"],
                   less_than=_dec(d["less_than"]),
                   in_values=(None if d["in_values"] is None
                              else [_dec(v) for v in d["in_values"]]))


@dataclass
class PartitionInfo:
    """reference: parser/model/model.go PartitionInfo (Type/Expr/Definitions).
    The expr is restricted to a bare column or YEAR/MONTH/TO_DAYS(col) —
    enough for the MySQL-typical layouts while keeping row routing a pure
    function of one column's internal value."""
    type: str = "range"          # range | hash | list
    expr: str = ""               # restored SQL text of the partition expr
    col_name: str = ""           # the column the expr reads
    func: str = ""               # "" (bare column) | year | month | to_days
    num: int = 0                 # hash partition count
    defs: list = field(default_factory=list)   # [PartitionDef]

    def find_def(self, name: str):
        lname = name.lower()
        for d in self.defs:
            if d.name.lower() == lname:
                return d
        return None

    def to_json(self):
        return {"type": self.type, "expr": self.expr,
                "col_name": self.col_name, "func": self.func, "num": self.num,
                "defs": [d.to_json() for d in self.defs]}

    @classmethod
    def from_json(cls, d):
        return cls(type=d["type"], expr=d["expr"], col_name=d["col_name"],
                   func=d["func"], num=d["num"],
                   defs=[PartitionDef.from_json(x) for x in d["defs"]])


@dataclass
class TableInfo:
    id: int = 0
    name: str = ""
    columns: list = field(default_factory=list)   # [ColumnInfo]
    indexes: list = field(default_factory=list)   # [IndexInfo]
    state: int = SchemaState.PUBLIC
    pk_is_handle: bool = False      # int PK stored as the row handle
    pk_col_id: int = 0
    auto_increment: int = 1
    max_col_id: int = 0
    max_idx_id: int = 0
    comment: str = ""
    update_ts: int = 0
    partition: PartitionInfo = None
    # view definition (reference: parser/model/model.go ViewInfo):
    # {"select": sql_text, "cols": [names], "definer": str} or None
    view: dict = None
    # sequence definition (reference: model.go SequenceInfo):
    # {"start","increment","min","max","cache","cycle"} or None
    sequence: dict = None
    temporary: bool = False   # session-local table (table/temptable role)
    # FK metadata (reference: model.go FKInfo — stored + shown, not
    # enforced, matching the v5.x reference default):
    # [{"name","cols","ref_table","ref_cols","on_delete","on_update"}]
    foreign_keys: list = field(default_factory=list)
    cached: bool = False      # ALTER TABLE ... CACHE (table/cache.go role)
    auto_random_bits: int = 0  # AUTO_RANDOM shard bits (meta/autoid)

    @property
    def is_view(self):
        return self.view is not None

    @property
    def is_sequence(self):
        return self.sequence is not None

    def public_columns(self):
        return [c for c in self.columns if c.state == SchemaState.PUBLIC]

    def writable_columns(self):
        return [c for c in self.columns if c.state >= SchemaState.WRITE_ONLY]

    def find_column(self, name: str):
        lname = name.lower()
        for c in self.columns:
            if c.name.lower() == lname:
                return c
        return None

    def find_index(self, name: str):
        lname = name.lower()
        for i in self.indexes:
            if i.name.lower() == lname:
                return i
        return None

    def to_json(self):
        return {
            "id": self.id, "name": self.name, "state": self.state,
            "pk_is_handle": self.pk_is_handle, "pk_col_id": self.pk_col_id,
            "auto_increment": self.auto_increment,
            "max_col_id": self.max_col_id, "max_idx_id": self.max_idx_id,
            "comment": self.comment, "update_ts": self.update_ts,
            "columns": [c.to_json() for c in self.columns],
            "indexes": [i.to_json() for i in self.indexes],
            "partition": (self.partition.to_json()
                          if self.partition is not None else None),
            "view": self.view,
            "sequence": self.sequence,
            "temporary": self.temporary,
            "foreign_keys": self.foreign_keys,
            "cached": self.cached,
            "auto_random_bits": self.auto_random_bits,
        }

    @classmethod
    def from_json(cls, d):
        return cls(
            id=d["id"], name=d["name"], state=d["state"],
            pk_is_handle=d["pk_is_handle"], pk_col_id=d["pk_col_id"],
            auto_increment=d["auto_increment"], max_col_id=d["max_col_id"],
            max_idx_id=d["max_idx_id"], comment=d.get("comment", ""),
            update_ts=d.get("update_ts", 0),
            columns=[ColumnInfo.from_json(c) for c in d["columns"]],
            indexes=[IndexInfo.from_json(i) for i in d["indexes"]],
            partition=(PartitionInfo.from_json(d["partition"])
                       if d.get("partition") else None),
            view=d.get("view"),
            sequence=d.get("sequence"),
            temporary=d.get("temporary", False),
            foreign_keys=d.get("foreign_keys", []),
            cached=d.get("cached", False),
            auto_random_bits=d.get("auto_random_bits", 0),
        )


@dataclass
class DBInfo:
    id: int = 0
    name: str = ""
    state: int = SchemaState.PUBLIC
    charset: str = "utf8mb4"
    collate: str = "utf8mb4_bin"

    def to_json(self):
        return {"id": self.id, "name": self.name, "state": self.state,
                "charset": self.charset, "collate": self.collate}

    @classmethod
    def from_json(cls, d):
        return cls(**d)


# -- DDL job (reference: parser/model/ddl.go model.Job) ----------------------

class JobState:
    NONE = 0
    RUNNING = 1
    ROLLINGBACK = 2
    ROLLBACK_DONE = 3
    DONE = 4
    CANCELLED = 5
    SYNCED = 6

    NAMES = {0: "none", 1: "running", 2: "rollingback", 3: "rollback done",
             4: "done", 5: "cancelled", 6: "synced"}


@dataclass
class Job:
    id: int = 0
    type: str = ""          # create_table | add_index | ...
    schema_id: int = 0
    table_id: int = 0
    state: int = JobState.NONE
    schema_state: int = SchemaState.NONE
    args: dict = field(default_factory=dict)
    error: str = ""
    row_count: int = 0      # backfill progress
    reorg_handle: int = 0   # backfill checkpoint (reference: ddl/reorg.go)
    schema_version: int = 0
    start_ts: int = 0

    def to_json(self):
        return json.dumps(self.__dict__, default=_enc)

    @classmethod
    def from_json(cls, s):
        d = json.loads(s)
        return cls(**d)


def _enc(v):
    if isinstance(v, bytes):
        return {"__b__": v.hex()}
    return v


def _dec(v):
    if isinstance(v, dict) and "__b__" in v:
        return bytes.fromhex(v["__b__"])
    return v
