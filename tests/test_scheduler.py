"""Serving front end (executor/scheduler.py): fragment admission, WFQ
across tenants, per-tenant running caps, batch-key coalescing, classified
DeviceAdmissionError (9009, taxonomy class `admission`) degrading to the
host engine, gauge surfacing across EXPLAIN ANALYZE / observe / HTTP
status, the multi-tenant breaker probe-owner fix, and the
no-direct-dispatch AST lint."""

import ast
import json
import os
import threading
import time
import urllib.request

import pytest

from tidb_tpu.errors import DeviceAdmissionError
from tidb_tpu.executor import scheduler
from tidb_tpu.executor.circuit import CircuitBreaker, get_breaker
from tidb_tpu.testkit import TestKit
from tidb_tpu.utils import failpoint
from tidb_tpu.utils.backoff import classify

AGG_Q = "select g, sum(v), count(*) from t group by g order by g"


@pytest.fixture(scope="module")
def tk():
    tk = TestKit()
    tk.must_exec("use test")
    tk.must_exec("create table t (id int primary key, g int, v int)")
    tk.must_exec("insert into t values " + ",".join(
        f"({i},{i % 5},{(i * 3) % 17})" for i in range(300)))
    tk.must_exec("set tidb_executor_engine = 'tpu'")
    return tk


@pytest.fixture()
def sched_sandbox():
    """Isolated scheduler state for policy-level tests (no live traffic
    in-process while unit tests drive the queues by hand)."""
    scheduler.reset_for_tests()
    saved = dict(scheduler._CFG)
    yield scheduler
    scheduler.reset_for_tests()
    scheduler._CFG.update(saved)


# -- classification / error surface ------------------------------------------

class TestAdmissionError:
    def test_errno_and_taxonomy(self):
        e = DeviceAdmissionError("queue full")
        assert e.code == 9009
        assert classify(e) == "admission"

    def test_injected_refusal_classifies_admission_not_fault(self):
        from tidb_tpu.utils.failpoint import InjectedAdmissionError
        with failpoint.enabled("device-admission", "admission-queue-full"):
            with pytest.raises(InjectedAdmissionError):
                failpoint.inject("device-admission")


# -- admission through real queries ------------------------------------------

class TestAdmissionPath:
    def test_normal_query_admits_and_releases(self, tk):
        before = scheduler.snapshot()["admitted"]
        rows = tk.must_query(AGG_Q).rows
        assert len(rows) == 5
        snap = scheduler.snapshot()
        assert snap["admitted"] > before
        assert scheduler.verify_drained()["ok"]

    def test_queue_full_degrades_to_host_exact(self, tk):
        """An admission refusal must not error: the fragment runs on the
        host engine, the result matches, and the per-tenant degradation
        gauge records it — the breaker is NOT charged (load != health)."""
        br = get_breaker(tk.session, shape="agg")
        fail0 = br.snapshot()["failures"]
        deg0 = scheduler.snapshot()["degradations_by_group"].get(
            "default", 0)
        with failpoint.enabled("device-admission", "admission-queue-full"):
            rows = tuple(map(tuple, tk.must_query(AGG_Q).rows))
        tk.must_exec("set tidb_executor_engine = 'host'")
        host = tuple(map(tuple, tk.must_query(AGG_Q).rows))
        tk.must_exec("set tidb_executor_engine = 'tpu'")
        assert rows == host
        assert br.snapshot()["failures"] == fail0
        snap = scheduler.snapshot()
        assert snap["degradations_by_group"]["default"] > deg0
        assert snap["rejected_injected"] >= 1

    def test_admission_wait_absorbed_and_counted(self, tk):
        waits0 = scheduler.snapshot()["sched_admission_waits_ms"]
        with failpoint.enabled("device-admission",
                               "1*admission-wait(0.05)"):
            rows = tk.must_query(AGG_Q).rows
        assert len(rows) == 5
        assert (scheduler.snapshot()["sched_admission_waits_ms"]
                >= waits0 + 40.0)

    def test_tenant_attribution(self, tk):
        wtk = tk.new_session()
        wtk.must_exec("use test")
        wtk.must_exec("set tidb_executor_engine = 'tpu'")
        wtk.must_exec("set tidb_resource_group = 'analytics'")
        with failpoint.enabled("device-admission", "admission-queue-full"):
            wtk.must_query(AGG_Q)
        assert scheduler.snapshot()["degradations_by_group"].get(
            "analytics", 0) >= 1

    def test_disabled_scheduler_passes_through(self, tk):
        tk.must_exec("set global tidb_device_sched_queue_depth = 0")
        try:
            admitted0 = scheduler.snapshot()["admitted"]
            rows = tk.must_query(AGG_Q).rows
            assert len(rows) == 5
            assert scheduler.snapshot()["admitted"] == admitted0
        finally:
            tk.must_exec("set global tidb_device_sched_queue_depth = 64")


# -- queueing policy (deterministic, by-hand queue state) --------------------

def _mk_ticket(group, batch_key=None):
    return scheduler.Ticket(group, "agg", batch_key)


def _enqueue(t):
    import collections
    scheduler._QUEUES.setdefault(
        t.group, collections.deque()).append(t)
    scheduler._QUEUED_N[0] += 1


class TestWFQPolicy:
    def test_equal_weights_interleave(self, sched_sandbox):
        """Starved-tenant regression at the policy level: a light tenant
        arriving AFTER a heavy tenant's backlog is granted interleaved,
        not behind the whole backlog (a FIFO queue would grant the light
        tickets last)."""
        scheduler._CFG.update({"cap": 0, "weights": {}})
        heavy = [_mk_ticket("heavy") for _ in range(8)]
        light = [_mk_ticket("light") for _ in range(2)]
        for t in heavy:
            _enqueue(t)
        for t in light:
            _enqueue(t)
        order = []
        with scheduler._LOCK:
            while scheduler._QUEUED_N[0]:
                assert scheduler._grant_some_locked()
                granted = [t for t in heavy + light
                           if t.granted.is_set() and t not in order]
                order.extend(granted)
        light_pos = [order.index(t) for t in light]
        # both light tickets granted within the first 4 grants (FIFO
        # would put them at positions 8 and 9)
        assert max(light_pos) <= 3, [t.group for t in order]

    def test_weights_bias_grant_share(self, sched_sandbox):
        """A 3x-weighted tenant gets ~3x the grants while both queues
        are backlogged (virtual time advances by 1/weight)."""
        scheduler._CFG.update({"cap": 0, "weights": {"gold": 3.0}})
        gold = [_mk_ticket("gold") for _ in range(9)]
        iron = [_mk_ticket("iron") for _ in range(9)]
        for t in gold + iron:
            _enqueue(t)
        order = []
        with scheduler._LOCK:
            for _ in range(8):  # first 8 grants while both backlogged
                assert scheduler._grant_some_locked()
                order.extend([t for t in gold + iron
                              if t.granted.is_set() and t not in order])
        n_gold = sum(1 for t in order if t.group == "gold")
        assert n_gold >= 5, f"gold got {n_gold}/8 grants"

    def test_tenant_running_cap_blocks_only_that_tenant(self,
                                                        sched_sandbox):
        scheduler._CFG.update({"cap": 2, "weights": {}})
        scheduler._RUNNING["busy"] = 2  # tenant at cap
        b = _mk_ticket("busy")
        o = _mk_ticket("other")
        _enqueue(b)
        _enqueue(o)
        with scheduler._LOCK:
            assert scheduler._grant_some_locked()
        assert o.granted.is_set() and not b.granted.is_set()
        # freeing one of busy's slots unblocks its queued ticket
        scheduler._RUNNING["busy"] = 1
        with scheduler._LOCK:
            assert scheduler._grant_some_locked()
        assert b.granted.is_set()

    def test_batch_key_followers_granted_together(self, sched_sandbox):
        """Queued tickets sharing the leader's compiled-pipeline identity
        coalesce onto one grant (small-fragment batching) — including
        followers from ANOTHER tenant's queue."""
        scheduler._CFG.update({"cap": 0, "weights": {}})
        key = ("agg", "sig", 512)
        lead = _mk_ticket("a", key)
        f1 = _mk_ticket("a", key)
        f2 = _mk_ticket("b", key)
        other = _mk_ticket("b", ("agg", "different", 512))
        for t in (lead, f1, f2, other):
            _enqueue(t)
        with scheduler._LOCK:
            assert scheduler._grant_some_locked()
        assert lead.granted.is_set() and not lead.batched
        assert f1.granted.is_set() and f1.batched
        assert f2.granted.is_set() and f2.batched
        assert not other.granted.is_set()
        assert scheduler.STATS["sched_batched_fragments"] == 2


class TestAdmitConcurrency:
    def test_timeout_rejects_cleanly(self, sched_sandbox):
        """A ticket that cannot be granted inside the admission timeout
        is refused with the classified error and leaves no queue residue."""
        scheduler._CFG.update({"depth": 8, "timeout_s": 0.05, "cap": 1,
                               "weights": {}})
        # the default tenant pinned at cap: the admit below must queue
        scheduler._RUNNING[scheduler.DEFAULT_GROUP] = 1
        with pytest.raises(DeviceAdmissionError):
            # ctx=None keeps the pinned config (no GLOBAL refresh)
            scheduler.admit(None, shape="agg")
        scheduler._RUNNING.clear()
        assert scheduler.verify_drained()["ok"]
        assert scheduler.STATS["rejected_timeout"] == 1

    def test_queue_full_rejects_excess(self, sched_sandbox):
        """At the global bound a group at/over its share of the depth is
        refused — here the backlog belongs to the refused group itself
        (the single-tenant case: share == the whole depth)."""
        scheduler._CFG.update({"depth": 2, "timeout_s": 0.05, "cap": 1,
                               "weights": {}})
        scheduler._RUNNING[scheduler.DEFAULT_GROUP] = 1
        for t in (_mk_ticket(scheduler.DEFAULT_GROUP),
                  _mk_ticket(scheduler.DEFAULT_GROUP)):
            _enqueue(t)
        with pytest.raises(DeviceAdmissionError) as ei:
            scheduler.admit(None, shape="agg")
        assert "queue full" in str(ei.value)
        assert scheduler.STATS["rejected_full"] == 1

    def test_queue_full_spares_under_share_group(self, sched_sandbox):
        """One tenant's backlog at the global depth must not refuse an
        under-share tenant's ticket: WFQ can only protect tickets that
        got INTO the queue, so the depth bound is per-group fair at the
        margin (the light ticket enqueues and is granted — its group has
        a free running slot — while the hog stays capped)."""
        scheduler._CFG.update({"depth": 2, "timeout_s": 5.0, "cap": 1,
                               "weights": {}})
        scheduler._RUNNING["hog"] = 1  # hog at cap: its backlog can't move
        for t in (_mk_ticket("hog"), _mk_ticket("hog")):
            _enqueue(t)
        t = scheduler.admit(None, shape="agg")  # default group, 0 queued
        assert t is not None and t.granted.is_set()
        scheduler.release(t)
        assert scheduler.STATS["rejected_full"] == 0

    def test_queue_backstop_bounds_total(self, sched_sandbox):
        """The fairness margin is itself bounded: at 2*depth the queue
        refuses EVERY group, share or not."""
        scheduler._CFG.update({"depth": 2, "timeout_s": 0.05, "cap": 1,
                               "weights": {}})
        scheduler._RUNNING["a"] = 1
        scheduler._RUNNING["b"] = 1
        for g in ("a", "a", "b", "b"):
            _enqueue(_mk_ticket(g))  # total = 4 = 2*depth
        with pytest.raises(DeviceAdmissionError):
            scheduler.admit(None, shape="agg")  # fresh group, 0 queued
        assert scheduler.STATS["rejected_full"] == 1

    def test_group_stat_cardinality_capped(self, sched_sandbox):
        """Group names are a free-form session sysvar: a client SETting a
        fresh name per connection must not grow the per-group stat lines
        (and their observe//metrics series) forever — past the cap, new
        names fold into one overflow bucket."""
        for i in range(scheduler.GROUP_STATS_CAP + 10):
            scheduler.note_degradation(f"ephemeral-{i}")
        degs = scheduler.snapshot()["degradations_by_group"]
        assert len(degs) <= scheduler.GROUP_STATS_CAP + 1
        assert degs[scheduler.OVERFLOW_GROUP] == 10
        # the breaker's per-group reporting obeys the same cap
        br = CircuitBreaker(clock=time.monotonic)
        for i in range(scheduler.GROUP_STATS_CAP + 5):
            br.record_failure(ValueError("x"), group=f"eph-{i}")
        by_group = br.snapshot()["by_group"]
        assert len(by_group) <= scheduler.GROUP_STATS_CAP + 1
        assert by_group[scheduler.OVERFLOW_GROUP]["failures"] == 5

    def test_concurrent_admit_release_drains(self, sched_sandbox):
        """N threads admit/release in a storm; afterwards nothing is
        queued or running (the chaos no-leaked-tickets invariant)."""
        scheduler._CFG.update({"depth": 64, "timeout_s": 5.0, "cap": 2,
                               "weights": {}})
        errs = []

        def worker(tid):
            try:
                for _ in range(25):
                    t = scheduler.admit(None, shape="agg")
                    time.sleep(0.0005)
                    scheduler.release(t)
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        ts = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(30)
        assert not errs
        deadline = time.monotonic() + 5
        while (not scheduler.verify_drained()["ok"]
               and time.monotonic() < deadline):
            time.sleep(0.01)
        assert scheduler.verify_drained()["ok"]

    def test_kill_interrupts_queued_wait(self, sched_sandbox):
        """KILL answers within ~a poll tick while the ticket is QUEUED
        (the PR 3 responsiveness discipline) — even with
        tidb_device_admission_timeout=0 (wait forever) — and the
        interrupted ticket leaves no queue residue."""
        scheduler._CFG.update({"depth": 8, "timeout_s": 0.0, "cap": 1,
                               "weights": {}})
        scheduler._RUNNING[scheduler.DEFAULT_GROUP] = 1  # force queueing

        class _Killed(Exception):
            pass

        class _Ctx:
            killed = False

            def check_killed(self):
                if self.killed:
                    raise _Killed()

        ctx = _Ctx()
        out = {}

        def waiter():
            try:
                scheduler.admit(ctx, shape="agg")
                out["r"] = "granted"
            except _Killed:
                out["r"] = "killed"
            except Exception as e:  # noqa: BLE001
                out["r"] = e

        t = threading.Thread(target=waiter)
        t.start()
        deadline = time.monotonic() + 5
        while scheduler.queue_depth() < 1 and time.monotonic() < deadline:
            time.sleep(0.005)
        t0 = time.monotonic()
        ctx.killed = True
        t.join(5)
        assert not t.is_alive()
        assert out["r"] == "killed"
        assert time.monotonic() - t0 < 1.0  # ~poll-tick, not wait-long
        scheduler._RUNNING.clear()
        assert scheduler.verify_drained()["ok"]

    def test_cross_session_batching_live(self, tk):
        """Two sessions queue the SAME agg fragment behind a saturated
        tenant; when the slot frees, the scheduler grants them as one
        batch (the second rides the first's grant — and both reuse the
        shared compiled pipeline)."""
        tk.must_exec("set global tidb_device_tenant_running_cap = 1")
        try:
            batched0 = scheduler.snapshot()["sched_batched_fragments"]
            # occupy the 'default' tenant's single slot so both queries
            # below must QUEUE (the batching window)
            blocker = scheduler.admit(tk.session, shape="agg")
            assert blocker is not None
            results, errors = [], []

            def q():
                s = tk.new_session()
                s.must_exec("use test")
                s.must_exec("set tidb_executor_engine = 'tpu'")
                try:
                    results.append(tuple(map(tuple,
                                             s.must_query(AGG_Q).rows)))
                except Exception as e:  # noqa: BLE001
                    errors.append(e)

            ts = [threading.Thread(target=q) for _ in range(2)]
            for t in ts:
                t.start()
            # let both enqueue behind the blocker, then free the slot
            deadline = time.monotonic() + 5
            while (scheduler.queue_depth() < 2
                   and time.monotonic() < deadline):
                time.sleep(0.005)
            assert scheduler.queue_depth() >= 2
            scheduler.release(blocker)
            for t in ts:
                t.join(30)
            assert not errors
            assert len(results) == 2 and results[0] == results[1]
            assert (scheduler.snapshot()["sched_batched_fragments"]
                    > batched0)
        finally:
            tk.must_exec("set global tidb_device_tenant_running_cap = 4")


# -- gauges across the observability surfaces --------------------------------

class _FakeDom:
    def __init__(self, **gv):
        self.global_vars = dict(gv)


class _FakeCtx:
    def __init__(self, **gv):
        self.domain = _FakeDom(**gv)


class TestCfgRefreshAtomicity:
    """Regression for the ISSUE-11 guarded-state race: _refresh_cfg used
    to write the raw-weights memo and the parsed weights OUTSIDE _LOCK.
    Two concurrent refreshes could interleave the `raw != memo` check
    with the two writes, leaving the memo naming config X while the
    weights held the parse of config Y — and because the memo matched,
    the stale weights STUCK until the sysvar changed again."""

    def _restore_raw(self):
        saved = scheduler._CFG_RAW_WEIGHTS[0]

        def fin():
            scheduler._CFG_RAW_WEIGHTS[0] = saved
        return fin

    def test_parse_and_publish_run_under_lock(self, sched_sandbox,
                                              monkeypatch, request):
        """The fixed interleaving, proven deterministically: the weight
        parse and both publishes happen inside one _LOCK hold, so no
        second refresh can slip between the memo check and the writes."""
        request.addfinalizer(self._restore_raw())
        scheduler._CFG_RAW_WEIGHTS[0] = ""
        held_during_parse = []
        real = scheduler._parse_weights

        def instrumented(raw):
            held_during_parse.append(scheduler._LOCK.locked())
            return real(raw)

        monkeypatch.setattr(scheduler, "_parse_weights", instrumented)
        depth = scheduler._refresh_cfg(
            _FakeCtx(tidb_device_wfq_weights="a:2,b:3"))
        assert depth == 64  # the caller's disabled-check snapshot
        assert held_during_parse == [True]
        assert scheduler._CFG["weights"] == {"a": 2.0, "b": 3.0}
        assert scheduler._CFG_RAW_WEIGHTS[0] == "a:2,b:3"

    def test_memo_never_splits_from_weights_threaded(self, sched_sandbox,
                                                     request):
        """Chaos-visible invariant: after any storm of concurrent
        refreshes against different weight configs, the published
        weights are exactly the parse of the published memo."""
        request.addfinalizer(self._restore_raw())
        scheduler._CFG_RAW_WEIGHTS[0] = ""
        ctxs = [_FakeCtx(tidb_device_wfq_weights=w)
                for w in ("a:2,b:1", "a:1,b:4", "c:9")]
        stop = threading.Event()
        errs = []

        def worker(i):
            k = 0
            try:
                while not stop.is_set():
                    scheduler._refresh_cfg(ctxs[(i + k) % len(ctxs)])
                    k += 1
            except Exception as e:  # pragma: no cover - fail loudly
                errs.append(e)

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        time.sleep(0.25)
        stop.set()
        for t in threads:
            t.join()
        assert not errs
        assert scheduler._parse_weights(scheduler._CFG_RAW_WEIGHTS[0]) \
            == scheduler._CFG["weights"]


class TestSchedulerObservability:
    def test_explain_analyze_and_observe_and_http(self, tk):
        with failpoint.enabled("device-admission", "admission-queue-full"):
            tk.must_query(AGG_Q)
        rows = tk.must_query(f"explain analyze {AGG_Q}").rows
        blob = "\n".join(" ".join(str(c) for c in r) for r in rows)
        assert "sched_queue_depth" in blob
        assert "sched_degradations" in blob

        g = tk.domain.observe.gauge_snapshot()
        assert "sched_queue_depth" in g
        assert any(k.startswith("sched_degradations:") for k in g)

        from tidb_tpu.server.http_status import StatusServer
        srv = StatusServer(tk.domain, port=0).start()
        try:
            st = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/status", timeout=5).read())
            assert "device_scheduler" in st
            assert st["device_scheduler"]["admitted"] >= 1
            assert "device_breakers" in st
            for snap in st["device_breakers"].values():
                assert "by_group" in snap
            met = urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics",
                timeout=5).read().decode()
            assert "sched_queue_depth" in met
            assert 'sched_degradations{resource_group=' in met
            # valid text exposition: at most ONE TYPE line per metric
            # (duplicates fail the entire Prometheus scrape)
            type_lines = [ln for ln in met.splitlines()
                          if ln.startswith("# TYPE ")]
            assert len(type_lines) == len(set(type_lines)), type_lines
        finally:
            srv.shutdown()


# -- multi-tenant breaker probe ownership ------------------------------------

class TestBreakerMultiTenantProbe:
    def test_two_sessions_one_thread_single_probe(self):
        """Two sessions multiplexed on ONE thread (the embedded-server
        shape): after cooldown, session A wins the probe slot; session
        B's allow() on the same thread must NOT be granted a second
        probe, and B's STALE success must not close the breaker out from
        under A's probe (the cross-session half-open race)."""
        clock = [0.0]
        br = CircuitBreaker(threshold=1, cooldown_s=10.0,
                            clock=lambda: clock[0], shape="agg")
        br.record_failure(RuntimeError("XlaRuntimeError: boom"),
                          session=7, group="a")
        assert br.state == "open"
        clock[0] = 11.0  # cooldown elapsed -> HALF_OPEN
        assert br.allow(session=1, group="a") is True      # A probes
        assert br.allow(session=2, group="b") is False     # B degrades
        # B's stale verdicts (same THREAD, different session) must not
        # resolve A's probe either way
        br.record_success(session=2)
        assert br.state == "half-open"
        br.record_failure(RuntimeError("XlaRuntimeError: boom"),
                          session=2, group="b")
        assert br.state == "half-open"
        # B cannot free A's probe slot
        br.release_probe(session=2)
        assert br.allow(session=3, group="c") is False
        # A's own verdict closes
        br.record_success(session=1)
        assert br.state == "closed"

    def test_worker_thread_verdict_resolves_probe(self):
        """A SUPERVISED probe fragment records its verdict from a worker
        thread (mpp_exec's exchange-exhaustion path): the session-keyed
        owner token must still match, re-opening the breaker — a
        (thread, session) token would misread it as stale and let the
        sick device be probed again immediately."""
        clock = [0.0]
        br = CircuitBreaker(threshold=1, cooldown_s=10.0,
                            clock=lambda: clock[0], shape="agg")
        br.record_failure(RuntimeError("XlaRuntimeError: boom"), session=5)
        clock[0] = 11.0
        assert br.allow(session=5) is True  # probe won on THIS thread
        t = threading.Thread(target=br.record_failure, args=(
            RuntimeError("XlaRuntimeError: boom"),), kwargs={"session": 5})
        t.start()
        t.join(5)
        assert br.state == "open", (
            "worker-thread probe verdict read as stale; breaker not "
            "re-opened")

    def test_per_group_stat_lines(self):
        br = CircuitBreaker(threshold=0, shape="agg")
        br.record_failure(RuntimeError("XlaRuntimeError: x"), group="t1")
        br.record_failure(RuntimeError("XlaRuntimeError: x"), group="t2")
        br.record_failure(RuntimeError("XlaRuntimeError: x"), group="t2")
        snap = br.snapshot()
        assert snap["by_group"]["t1"]["failures"] == 1
        assert snap["by_group"]["t2"]["failures"] == 2


# -- lint: no direct device dispatch bypassing admission ---------------------

#: files allowed to touch the supervisor dispatch directly: the
#: supervisor itself, the admission-aware run_device, the scheduler,
#: parallel/mpp.py's library-embedder hook (_supervised_step — audited:
#: it holds its own admission ticket around the supervised call), and
class TestNoDirectDispatchLint:
    def test_call_supervised_confined_to_admission_layer(self):
        """Registry rule (tidb_tpu/lint rules/confinement.py): direct
        call_supervised / supervised_call is confined to the admission
        layer (run_device admits first; the compile service's bounded
        worker pool is its own admission) — a new dispatch path must not
        silently bypass per-tenant scheduling."""
        from tidb_tpu.lint import run_rule
        findings = run_rule("supervised-confinement")
        assert not findings, [f.to_json() for f in findings]
