"""Protocol/auth breadth (VERDICT round-2 missing #10): caching_sha2
auth with AuthSwitch, server-side cursors + COM_STMT_FETCH,
COM_STMT_SEND_LONG_DATA / COM_STMT_RESET (reference: server/conn.go:810,
server/conn_stmt.go)."""

import socket
import struct

import pytest

from tidb_tpu.server import protocol as P
from tidb_tpu.server.packet import PacketIO, read_nul_str
from tidb_tpu.server.server import MySQLServer
from tidb_tpu.session import bootstrap_domain


class Client:
    """Mini client speaking enough of the protocol for these tests."""

    def __init__(self, port, user="root", password="",
                 plugin="mysql_native_password"):
        self.sock = socket.create_connection(("127.0.0.1", port),
                                             timeout=10)
        self.io = PacketIO(self.sock)
        self.fast_auth = False
        self._handshake(user, password, plugin)

    def _scramble(self, plugin, password, salt):
        if plugin == "caching_sha2_password":
            return P.caching_sha2_scramble(password.encode(), salt[:20])
        return P.native_password_hash(password.encode(), salt[:20])

    def _handshake(self, user, password, plugin):
        pkt = self.io.read_packet()
        _ver, pos = read_nul_str(pkt, 1)
        pos += 4
        salt = pkt[pos:pos + 8]
        pos += 9 + 2 + 1 + 2 + 2
        salt_len = pkt[pos]
        pos += 1 + 10
        salt += pkt[pos:pos + max(13, salt_len - 8) - 1]
        self.salt = salt[:20]
        caps = (P.CLIENT_PROTOCOL_41 | P.CLIENT_SECURE_CONNECTION
                | P.CLIENT_PLUGIN_AUTH)
        auth = self._scramble(plugin, password, self.salt)
        out = struct.pack("<I", caps) + struct.pack("<I", 1 << 24)
        out += bytes([255]) + b"\x00" * 23
        out += user.encode() + b"\x00"
        out += bytes([len(auth)]) + auth
        out += plugin.encode() + b"\x00"
        self.io.write_packet(out)
        while True:
            resp = self.io.read_packet()
            if resp[:1] == b"\xfe":  # AuthSwitchRequest
                new_plugin, p2 = read_nul_str(resp, 1)
                new_salt = resp[p2:].rstrip(b"\x00")[:20]
                self.io.write_packet(self._scramble(
                    new_plugin.decode(), password, new_salt))
                continue
            if resp[:2] == P.FAST_AUTH_SUCCESS:
                self.fast_auth = True
                continue
            if resp[0] == 0xFF:
                code = struct.unpack_from("<H", resp, 1)[0]
                raise AssertionError(f"auth failed: {code}")
            assert resp[0] == 0x00
            return

    def cmd(self, cmd, payload=b"", expect_reply=True):
        self.io.reset_seq()
        self.io.write_packet(bytes([cmd]) + payload)
        return self.io.read_packet() if expect_reply else None

    def query_ok(self, sql):
        r = self.cmd(P.COM_QUERY, sql.encode())
        assert r[0] != 0xFF, r
        if r[0] != 0x00:  # resultset: drain defs + rows to trailing EOF
            self._drain_resultset()
        return r

    def _drain_resultset(self):
        eofs = 0
        while eofs < 2:
            pkt = self.io.read_packet()
            if pkt[:1] == b"\xfe" and len(pkt) < 9:
                eofs += 1

    def prepare(self, sql):
        r = self.cmd(P.COM_STMT_PREPARE, sql.encode())
        assert r[0] == 0x00
        sid = struct.unpack_from("<I", r, 1)[0]
        ncols = struct.unpack_from("<H", r, 5)[0]
        nparams = struct.unpack_from("<H", r, 7)[0]
        for _ in range(nparams):
            self.io.read_packet()
        if nparams:
            self.io.read_packet()  # eof
        for _ in range(ncols):
            self.io.read_packet()
        if ncols:
            self.io.read_packet()  # eof
        return sid, ncols, nparams

    def close(self):
        try:
            self.cmd(P.COM_QUIT, expect_reply=False)
        finally:
            self.sock.close()


@pytest.fixture(scope="module")
def server():
    dom = bootstrap_domain()
    srv = MySQLServer(dom, port=0)
    srv.start()
    from tidb_tpu.session import new_session
    s = new_session(dom)
    s.execute("create user 'sha2user'@'%' identified with "
              "'caching_sha2_password' by 'secret2'")
    s.execute("create user 'nativeuser'@'%' identified by 'secret1'")
    s.execute("grant all on *.* to 'sha2user'@'%'")
    s.execute("grant all on *.* to 'nativeuser'@'%'")
    s.execute("create database pb")
    s.execute("use pb")
    s.execute("create table t (id int primary key, v varchar(2000))")
    s.execute("insert into t values " + ",".join(
        f"({i}, 'row{i}')" for i in range(25)))
    yield srv
    srv.shutdown()


class TestCachingSha2:
    def test_direct_sha2_login_fast_path(self, server):
        c = Client(server.port, "sha2user", "secret2",
                   plugin="caching_sha2_password")
        assert c.fast_auth  # 0x01 0x03 marker seen
        c.query_ok("select 1")
        c.close()

    def test_auth_switch_from_native_client(self, server):
        # client starts with native scramble; server switches it to sha2
        c = Client(server.port, "sha2user", "secret2",
                   plugin="mysql_native_password")
        c.query_ok("select 1")
        c.close()

    def test_auth_switch_to_native(self, server):
        # sha2-first client hitting a native account gets switched back
        c = Client(server.port, "nativeuser", "secret1",
                   plugin="caching_sha2_password")
        c.query_ok("select 1")
        c.close()

    def test_wrong_password_rejected(self, server):
        with pytest.raises(AssertionError, match="auth failed"):
            Client(server.port, "sha2user", "wrong",
                   plugin="caching_sha2_password")


class TestCursorFetch:
    def test_cursor_execute_then_fetch_pages(self, server):
        c = Client(server.port, "sha2user", "secret2",
                   plugin="caching_sha2_password")
        c.query_ok("use pb")
        sid, ncols, nparams = c.prepare(
            "select id from t order by id")
        assert (ncols, nparams) == (1, 0)
        # execute with CURSOR_TYPE_READ_ONLY: defs + EOF(cursor exists)
        payload = (struct.pack("<I", sid)
                   + bytes([P.CURSOR_TYPE_READ_ONLY])
                   + struct.pack("<I", 1))
        c.io.reset_seq()
        c.io.write_packet(bytes([P.COM_STMT_EXECUTE]) + payload)
        colcount = c.io.read_packet()
        assert colcount[0] == 1
        c.io.read_packet()  # column def
        eof = c.io.read_packet()
        status = struct.unpack_from("<H", eof, 3)[0]
        assert status & P.SERVER_STATUS_CURSOR_EXISTS

        got = []
        last = False
        while not last:
            c.io.reset_seq()
            c.io.write_packet(bytes([P.COM_STMT_FETCH])
                              + struct.pack("<I", sid)
                              + struct.pack("<I", 10))
            while True:
                pkt = c.io.read_packet()
                if pkt[:1] == b"\xfe" and len(pkt) < 9:
                    st = struct.unpack_from("<H", pkt, 3)[0]
                    last = bool(st & P.SERVER_STATUS_LAST_ROW_SENT)
                    break
                # binary row: header 0x00, nullmap, int value
                got.append(struct.unpack_from(
                    "<i", pkt, 1 + (1 + 2 + 7) // 8)[0])
        assert got == list(range(25))
        c.close()


class TestLongData:
    def test_send_long_data_param(self, server):
        c = Client(server.port, "sha2user", "secret2",
                   plugin="caching_sha2_password")
        c.query_ok("use pb")
        sid, _nc, nparams = c.prepare("insert into t values (100, ?)")
        assert nparams == 1
        big = "A" * 600 + "B" * 600
        # two chunks, no server response for either
        c.io.reset_seq()
        c.io.write_packet(bytes([P.COM_STMT_SEND_LONG_DATA])
                          + struct.pack("<I", sid) + struct.pack("<H", 0)
                          + big[:600].encode())
        c.io.reset_seq()
        c.io.write_packet(bytes([P.COM_STMT_SEND_LONG_DATA])
                          + struct.pack("<I", sid) + struct.pack("<H", 0)
                          + big[600:].encode())
        # execute: param 0 comes from the long data; types still bound
        payload = (struct.pack("<I", sid) + bytes([0])
                   + struct.pack("<I", 1)
                   + bytes([0])        # null bitmap
                   + bytes([1])        # new params bound
                   + bytes([0xFE, 0]))  # MYSQL_TYPE_STRING
        c.io.reset_seq()
        c.io.write_packet(bytes([P.COM_STMT_EXECUTE]) + payload)
        ok = c.io.read_packet()
        assert ok[0] == 0x00
        r = c.cmd(P.COM_QUERY,
                  b"select length(v) from t where id = 100")
        assert r[0] != 0xFF
        c._drain_resultset()
        # verify via a fresh query through another path
        c2 = Client(server.port, "sha2user", "secret2",
                    plugin="caching_sha2_password")
        c2.query_ok("use pb")
        sid2, _, _ = c2.prepare("select v from t where id = 100")
        payload = (struct.pack("<I", sid2) + bytes([0])
                   + struct.pack("<I", 1))
        c2.io.reset_seq()
        c2.io.write_packet(bytes([P.COM_STMT_EXECUTE]) + payload)
        c2.io.read_packet()  # col count
        c2.io.read_packet()  # def
        c2.io.read_packet()  # eof
        row = c2.io.read_packet()
        assert big.encode() in row
        c2.close()
        # reset clears the long data buffer
        reset = c.cmd(P.COM_STMT_RESET, struct.pack("<I", sid))
        assert reset[0] == 0x00
        c.close()


def test_alter_user_rejects_unknown_plugin():
    from tidb_tpu.errors import TiDBError
    from tidb_tpu.session import bootstrap_domain, new_session
    s = new_session(bootstrap_domain())
    s.execute("create user 'pu'@'%' identified by 'x'")
    try:
        s.execute("alter user 'pu'@'%' identified with 'bogus_plugin' by 'y'")
        raise AssertionError("expected error 1524")
    except TiDBError as e:
        assert e.code == 1524
    try:
        s.execute("create user 'pu2'@'%' identified with "
                  "'evil'', super_priv=''Y' by 'y'")
        raise AssertionError("expected error 1524")
    except TiDBError as e:
        assert e.code == 1524
