"""Failpoint-style fault injection (reference: pingcap/failpoint, used in
103 reference files; kv/fault_injection.go).

Production code calls ``inject("name")`` at interesting points; tests
activate behaviors with ``enable``:

    failpoint.enable("commit-after-prewrite", "panic")     # raise
    failpoint.enable("backfill-batch", "sleep(0.05)")
    failpoint.enable("scan-rows", "return(7)")

Disabled failpoints cost one dict lookup. ``inject`` returns the
``return(...)`` payload (or None), raises FailpointError for ``panic``."""

from __future__ import annotations

import re
import threading
import time


class FailpointError(Exception):
    """Raised by an enabled `panic` failpoint."""


_lock = threading.Lock()
_active: dict[str, str] = {}
_hits: dict[str, int] = {}


def enable(name: str, action: str):
    with _lock:
        _active[name] = action
        _hits[name] = 0


def disable(name: str):
    with _lock:
        _active.pop(name, None)


def disable_all():
    with _lock:
        _active.clear()


def hits(name: str) -> int:
    return _hits.get(name, 0)


def inject(name: str):
    action = _active.get(name)
    if action is None:
        return None
    with _lock:
        _hits[name] = _hits.get(name, 0) + 1
    if action == "panic":
        raise FailpointError(f"failpoint {name} triggered")
    m = re.fullmatch(r"sleep\(([\d.]+)\)", action)
    if m:
        time.sleep(float(m.group(1)))
        return None
    m = re.fullmatch(r"return\((.*)\)", action)
    if m:
        raw = m.group(1)
        try:
            return int(raw)
        except ValueError:
            return raw.strip("'\"")
    m = re.fullmatch(r"(\d+)\*panic", action)
    if m:  # N*panic: raise for the first N hits, then no-op
        if _hits.get(name, 0) <= int(m.group(1)):
            raise FailpointError(f"failpoint {name} triggered")
        return None
    raise ValueError(f"unknown failpoint action {action!r}")
