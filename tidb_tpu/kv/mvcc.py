"""Embedded MVCC store — the reference's unistore role
(store/mockstore/unistore/tikv/mvcc.go: Prewrite :596, Commit :907).

Percolator-style two-phase commit over an in-process sorted map:

- ``prewrite``  locks every mutated key (primary first, conceptually) after
  checking write conflicts (any commit newer than start_ts) and foreign locks.
- ``commit``    converts locks into versions at commit_ts.
- ``rollback``  removes locks and writes a rollback marker.

Reads at a timestamp see the newest version with commit_ts <= ts and raise
``LockedError`` on a conflicting lock (caller resolves; in-process that means
checking txn liveness and cleaning up, reference: resolveLocks).

Region abstraction included so the executor can fan out range scans the way
cop tasks split by region (reference: store/copr/coprocessor.go:170); splits
are metadata-only here since data lives in one process.
"""

from __future__ import annotations

import bisect
import itertools
import threading
import time

from ..errors import LockedError, WriteConflictError, DeadlockError

OP_PUT = 0
OP_DEL = 1
OP_LOCK = 2  # lock-only record (SELECT FOR UPDATE)

#: flag bit OR'd onto a prewrite op: skip the write-conflict check for
#: this key. The schema amender's injected index mutations are logically
#: sequenced AFTER the ADD INDEX backfill the txn just observed (the
#: amendment was computed FROM the post-DDL schema), so a backfill commit
#: past start_ts on exactly these keys is not a conflict (reference:
#: schema_amender.go's amended-mutation commit handling).
OP_AMEND_FLAG = 16
OP_ROLLBACK = 3


class TSOracle:
    """Timestamp oracle (the PD TSO role, reference: tidb-server/main.go:74).

    Hybrid physical/logical like TiDB: ts = physical_ms << 18 | logical.

    THE oracle abstraction: everything that needs a timestamp — 2PC
    start/commit ts, raw_put's self-allocated commit_ts, snapshot read
    views — calls ``next_ts()`` on the engine's ``tso`` slot.  Engines
    accept an injected oracle so fleet mode
    (kv/shared_store.SegmentTSOracle: batched leases off the shared
    segment counter, fleet-monotonic) and solo mode (this class) share
    one code path; nothing may mint a timestamp any other way.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._last_phys = 0
        self._logical = 0

    def next_ts(self) -> int:
        with self._lock:
            phys = int(time.time() * 1000)
            if phys <= self._last_phys:
                phys = self._last_phys
                self._logical += 1
            else:
                self._last_phys = phys
                self._logical = 0
            if self._logical >= (1 << 18):
                self._last_phys = phys + 1
                self._logical = 0
                phys += 1
            return (phys << 18) | self._logical

    def advance_to(self, ts: int):
        """Never issue a timestamp <= ``ts`` again.  Recovery calls this
        with the log's high-water: a restarted process in the same
        millisecond as the crash must not mint timestamps below versions
        it just replayed (they would be invisible to new snapshots)."""
        with self._lock:
            phys, logical = ts >> 18, ts & 0x3FFFF
            if phys > self._last_phys:
                self._last_phys, self._logical = phys, logical
            elif phys == self._last_phys and logical > self._logical:
                self._logical = logical


class Lock:
    __slots__ = ("start_ts", "primary", "op", "value", "ttl")

    def __init__(self, start_ts, primary, op, value=None, ttl=3000):
        self.start_ts = start_ts
        self.primary = primary
        self.op = op
        self.value = value
        self.ttl = ttl


class Region:
    """Key-range shard (reference: ~100MiB Regions; here metadata for
    parallel scan fan-out)."""

    _ids = itertools.count(2)

    def __init__(self, start: bytes, end: bytes, region_id=None):
        self.id = region_id if region_id is not None else next(Region._ids)
        self.start = start
        self.end = end  # b"" means +inf

    def contains(self, key: bytes) -> bool:
        return self.start <= key and (not self.end or key < self.end)

    def __repr__(self):
        return f"Region({self.id}, {self.start!r}..{self.end!r})"


class _SortedMap:
    """Sorted key → version-chain map. Python list + bisect now; the C++
    engine replaces this class behind the same five methods."""

    def __init__(self):
        self.keys: list[bytes] = []
        self.vals: dict[bytes, list] = {}  # key -> [(commit_ts desc, start_ts, op, value)]

    def insert_version(self, key: bytes, commit_ts: int, start_ts: int, op: int, value):
        chain = self.vals.get(key)
        if chain is None:
            bisect.insort(self.keys, key)
            self.vals[key] = chain = []
        # keep strictly descending commit_ts order: rollback markers carry an
        # *old* start_ts and must not land at the head above newer commits
        # (has_commit_after/read rely on the ordering)
        i = 0
        while i < len(chain) and chain[i][0] > commit_ts:
            i += 1
        chain.insert(i, (commit_ts, start_ts, op, value))

    def read(self, key: bytes, ts: int):
        """newest version with commit_ts <= ts -> (op, value) or None."""
        chain = self.vals.get(key)
        if not chain:
            return None
        for commit_ts, _start, op, value in chain:
            if commit_ts <= ts and op != OP_ROLLBACK:
                return (op, value)
        return None

    def range_keys(self, start: bytes, end: bytes):
        lo = bisect.bisect_left(self.keys, start)
        hi = bisect.bisect_left(self.keys, end) if end else len(self.keys)
        return self.keys[lo:hi]

    def has_commit_after(self, key: bytes, ts: int):
        """-> commit_ts of any non-rollback commit with commit_ts > ts, else 0.
        Rollback markers above ts are skipped, not treated as commits."""
        chain = self.vals.get(key)
        if not chain:
            return 0
        for commit_ts, _start, op, _value in chain:
            if commit_ts <= ts:
                break
            if op != OP_ROLLBACK:
                return commit_ts
        return 0

    def has_rollback(self, key: bytes, start_ts: int) -> bool:
        chain = self.vals.get(key)
        if not chain:
            return False
        return any(st == start_ts and op == OP_ROLLBACK for _c, st, op, _v in chain)


class MVCCStore:
    """The embedded transactional store. Thread-safe via a coarse RLock —
    single-process control plane; scan hot paths hand out columnar data
    through the columnar cache, not per-key reads."""

    def __init__(self, oracle=None):
        self._lock = threading.RLock()
        self.map = _SortedMap()
        self.locks: dict[bytes, Lock] = {}
        self.tso = oracle if oracle is not None else TSOracle()
        self.regions: list[Region] = [Region(b"", b"", region_id=1)]
        self.safe_point = 0  # GC safe point (reference: store/gcworker)
        # deadlock detection: start_ts -> start_ts it waits for
        self._waits: dict[int, int] = {}
        # table write watermark for columnar-cache invalidation
        self.table_versions: dict[int, int] = {}
        self.table_version_ts: dict[int, int] = {}

    # -- transactional API --------------------------------------------------

    def prewrite(self, mutations, primary: bytes, start_ts: int,
                 view_seq: "int | None" = None):
        """mutations: [(key, op, value)] with op in {OP_PUT, OP_DEL,
        OP_LOCK}, optionally OR'd with OP_AMEND_FLAG.  ``view_seq`` is
        the fleet read-view anchor (kv/shared_store overrides consume
        it); the solo store applies commits synchronously with ts
        order, so commit_ts comparison alone is already sound here."""
        with self._lock:
            for key, op, value in mutations:
                lock = self.locks.get(key)
                if lock is not None and lock.start_ts != start_ts:
                    raise LockedError(f"key locked by txn {lock.start_ts}",
                                      key=key, lock_ts=lock.start_ts)
                if lock is not None and lock.op == OP_LOCK:
                    # our own pessimistic lock: the conflict was already
                    # checked against for_update_ts at lock time (reference:
                    # TiKV pessimistic prewrite skips the write-conflict
                    # check for DoPessimisticCheck keys)
                    continue
                if op & OP_AMEND_FLAG:
                    continue  # amended key: no ts conflict (see flag doc)
                conflict = self.map.has_commit_after(key, start_ts)
                if conflict:
                    raise WriteConflictError(
                        f"write conflict: key committed at {conflict} > start {start_ts}")
                if self.map.has_rollback(key, start_ts):
                    raise WriteConflictError("transaction already rolled back")
            for key, op, value in mutations:
                self.locks[key] = Lock(start_ts, primary, op & ~OP_AMEND_FLAG,
                                       value)

    def commit(self, keys, start_ts: int, commit_ts: int):
        with self._lock:
            for key in keys:
                lock = self.locks.get(key)
                if lock is None or lock.start_ts != start_ts:
                    # already committed (idempotent) or rolled back
                    if self.map.has_rollback(key, start_ts):
                        raise WriteConflictError("txn rolled back before commit")
                    continue
                del self.locks[key]
                if lock.op != OP_LOCK:
                    self.map.insert_version(key, commit_ts, start_ts, lock.op, lock.value)

    def rollback(self, keys, start_ts: int):
        with self._lock:
            for key in keys:
                lock = self.locks.get(key)
                if lock is not None and lock.start_ts == start_ts:
                    del self.locks[key]
                self.map.insert_version(key, start_ts, start_ts, OP_ROLLBACK, None)
            self._waits.pop(start_ts, None)

    def acquire_pessimistic_lock(self, keys, primary: bytes, start_ts: int,
                                 for_update_ts: int,
                                 view_seq: "int | None" = None):
        """Pessimistic lock: conflict check against for_update_ts
        (reference: unistore PessimisticLock).  ``view_seq`` as in
        :meth:`prewrite` — solo stores ignore it."""
        with self._lock:
            for key in keys:
                lock = self.locks.get(key)
                if lock is not None and lock.start_ts != start_ts:
                    self._check_deadlock(start_ts, lock.start_ts)
                    raise LockedError(f"key locked by txn {lock.start_ts}",
                                      key=key, lock_ts=lock.start_ts)
                conflict = self.map.has_commit_after(key, for_update_ts)
                if conflict:
                    raise WriteConflictError(
                        f"pessimistic conflict at {conflict} > for_update {for_update_ts}")
            for key in keys:
                if key not in self.locks:
                    self.locks[key] = Lock(start_ts, primary, OP_LOCK)

    def _check_deadlock(self, waiter: int, holder: int):
        """Wait-for graph cycle check (reference: unistore/tikv/detector.go)."""
        self._waits[waiter] = holder
        seen = {waiter}
        cur = holder
        while cur in self._waits:
            cur = self._waits[cur]
            if cur in seen:
                self._waits.pop(waiter, None)
                raise DeadlockError("deadlock detected")
            seen.add(cur)

    def clear_wait(self, start_ts: int):
        with self._lock:
            self._waits.pop(start_ts, None)

    def resolve_lock(self, key: bytes, committed: bool, commit_ts: int = 0):
        """Resolve an orphan lock after checking its txn status
        (reference: GC worker resolveLocks)."""
        with self._lock:
            lock = self.locks.get(key)
            if lock is None:
                return
            if committed:
                self.commit([key], lock.start_ts, commit_ts)
            else:
                self.rollback([key], lock.start_ts)

    # -- reads --------------------------------------------------------------

    def get(self, key: bytes, ts: int, own_start_ts: int = 0):
        with self._lock:
            lock = self.locks.get(key)
            if (lock is not None and lock.start_ts != own_start_ts
                    and lock.op != OP_LOCK and lock.start_ts < ts):
                raise LockedError("read blocked by lock", key=key, lock_ts=lock.start_ts)
            res = self.map.read(key, ts)
            if res is None:
                return None
            op, value = res
            return value if op == OP_PUT else None

    def scan(self, start: bytes, end: bytes, ts: int, limit: int = 0,
             own_start_ts: int = 0):
        """-> [(key, value)] of live versions at ts, ascending."""
        with self._lock:
            out = []
            for key in self.map.range_keys(start, end):
                lock = self.locks.get(key)
                if (lock is not None and lock.start_ts != own_start_ts
                        and lock.op != OP_LOCK and lock.start_ts < ts):
                    raise LockedError("scan blocked by lock", key=key,
                                      lock_ts=lock.start_ts)
                res = self.map.read(key, ts)
                if res is not None and res[0] == OP_PUT:
                    out.append((key, res[1]))
                    if limit and len(out) >= limit:
                        break
            return out

    # -- raw (non-transactional, bootstrap/bulk-load/meta fast path) --------

    def raw_put(self, key: bytes, value: bytes, commit_ts: int | None = None):
        with self._lock:
            ts = commit_ts if commit_ts is not None else self.tso.next_ts()
            self.map.insert_version(key, ts, ts, OP_PUT, value)

    def raw_batch_put(self, pairs, commit_ts: int | None = None):
        with self._lock:
            ts = commit_ts if commit_ts is not None else self.tso.next_ts()
            for key, value in pairs:
                self.map.insert_version(key, ts, ts, OP_PUT, value)

    def raw_delete_range(self, start: bytes, end: bytes):
        """Physical unversioned removal (reference: gc_delete_range for
        dropped tables/indexes)."""
        with self._lock:
            for key in list(self.map.range_keys(start, end)):
                self.map.vals.pop(key, None)
            lo = bisect.bisect_left(self.map.keys, start)
            hi = bisect.bisect_left(self.map.keys, end) if end else len(self.map.keys)
            del self.map.keys[lo:hi]

    # -- GC -----------------------------------------------------------------

    def scan_locks(self, max_ts: int):
        """[(key, start_ts, primary)] for locks with start_ts <= max_ts
        (reference: gc_worker.go:1015 resolveLocks scan)."""
        with self._lock:
            return [(k, l.start_ts, l.primary)
                    for k, l in self.locks.items() if l.start_ts <= max_ts]

    def gc(self, safe_point: int):
        """Drop versions older than the newest one <= safe_point
        (reference: store/gcworker/gc_worker.go:619 runGCJob)."""
        with self._lock:
            self.safe_point = max(self.safe_point, safe_point)
            empty = []
            for key, chain in self.map.vals.items():
                keep = []
                kept_visible = False
                for ver in chain:
                    if ver[0] > safe_point:
                        keep.append(ver)
                    elif ver[2] == OP_ROLLBACK:
                        continue  # stale marker: never counts as the visible version
                    elif not kept_visible:
                        kept_visible = True
                        if ver[2] == OP_PUT:
                            keep.append(ver)
                    # older than first visible-at-safepoint: drop
                chain[:] = keep
                if not chain:
                    empty.append(key)
            for key in empty:
                del self.map.vals[key]
                idx = bisect.bisect_left(self.map.keys, key)
                if idx < len(self.map.keys) and self.map.keys[idx] == key:
                    del self.map.keys[idx]

    def key_count(self) -> int:
        return len(self.map.keys)

    def unwind_commit(self, keys, start_ts: int):
        """Remove committed versions stamped ``start_ts`` (the WAL's
        last-disposition-wins rule, kv/shared_store.py: a commit whose
        record landed but whose fsync FAILED was rolled back by its
        owner — a replica or recovery replaying commit-then-rollback
        must converge on the rollback, not resurrect the commit)."""
        with self._lock:
            for key in keys:
                chain = self.map.vals.get(key)
                if not chain:
                    continue
                chain[:] = [v for v in chain
                            if v[1] != start_ts or v[2] == OP_ROLLBACK]

    # -- durable snapshot (kv/wal.py checkpoint payload) ---------------------

    def dump_state(self) -> bytes:
        """Pickle the full engine state — version chains INCLUDING
        in-flight locks (a checkpoint taken mid-2PC keeps the locks; the
        WAL tail's commit/rollback record resolves them on replay)."""
        import pickle
        with self._lock:
            locks = {k: (l.start_ts, l.primary, l.op, l.value, l.ttl)
                     for k, l in self.locks.items()}
            return pickle.dumps({
                "keys": self.map.keys, "vals": self.map.vals,
                "locks": locks, "safe_point": self.safe_point,
                "table_versions": self.table_versions,
                "table_version_ts": self.table_version_ts,
                # TSO high-water: a restore must never mint below it
                "last_ts": self.tso.next_ts(),
            }, protocol=4)

    def load_state(self, blob: bytes):
        import pickle
        st = pickle.loads(blob)
        with self._lock:
            self.map.keys = list(st["keys"])
            self.map.vals = dict(st["vals"])
            self.locks = {k: Lock(*v) for k, v in st["locks"].items()}
            self.safe_point = st["safe_point"]
            self.table_versions = dict(st["table_versions"])
            self.table_version_ts = dict(st["table_version_ts"])
        adv = getattr(self.tso, "advance_to", None)
        if adv is not None and st.get("last_ts"):
            adv(st["last_ts"])

    def debug_chain(self, key: bytes):
        """[(commit_ts, start_ts, op, value)] newest-first (reference:
        the HTTP MVCC introspection API, server/http_handler.go)."""
        with self._lock:
            return [(c, s, op, v if op == OP_PUT else None)
                    for c, s, op, v in self.map.vals.get(key, [])]

    # -- regions ------------------------------------------------------------

    def split_region(self, split_key: bytes):
        with self._lock:
            for i, r in enumerate(self.regions):
                if r.contains(split_key) and r.start != split_key:
                    new = Region(split_key, r.end)
                    r.end = split_key
                    self.regions.insert(i + 1, new)
                    return new
            return None

    def regions_in_range(self, start: bytes, end: bytes):
        out = []
        for r in self.regions:
            if (not r.end or r.end > start) and (not end or r.start < end):
                out.append(r)
        return out

    # -- table write watermarks (columnar cache invalidation) ---------------

    def bump_table_version(self, table_id: int, commit_ts: int = 0) -> int:
        with self._lock:
            v = self.table_versions.get(table_id, 0) + 1
            self.table_versions[table_id] = v
            if commit_ts:
                self.table_version_ts[table_id] = commit_ts
            return v

    def table_version(self, table_id: int) -> int:
        return self.table_versions.get(table_id, 0)

    def table_version_info(self, table_id: int) -> tuple[int, int]:
        """(version, commit_ts of the last bump) — readers with snapshot ts
        older than that commit_ts must not be served the cached columns."""
        with self._lock:
            return (self.table_versions.get(table_id, 0),
                    self.table_version_ts.get(table_id, 0))
