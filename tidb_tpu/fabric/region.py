"""Region-sharded keyspace: host loss becomes a region failover.

The keyspace is partitioned into N contiguous regions (reference:
TiKV regions / PD placement).  Each region has its OWN write-ahead log
directory (kv/wal.region_dir), its own lease/epoch/committed-length/
applied-LSN cells in the coordination segment (fabric/coord REGIONS
block), and replicates checkpoint + committed WAL tail to an
object-store-shaped blob API (fabric/blob) under a MANIFEST written
last.  The pieces:

- :class:`RegionMap`        — key -> region id; range -> per-region spans
- :class:`RegionCoordView`  — epoch-fenced coordinator facade one region's
                              WAL and engine talk through; a stale epoch
                              (another host failed the region over) turns
                              every durability write into a loud
                              :class:`RegionEpochError` — the zombie
                              fence.
- :class:`RegionReplicator` — ship/restore checkpoint + tail blobs with
                              rename-last MANIFEST semantics.
- :class:`RegionStore`      — the router: one DurableMVCCStore per owned
                              region behind the exact kv/store.Storage
                              engine surface, so Transaction / Snapshot /
                              executors run unchanged.  Cross-region
                              scans fan out over split_range and
                              concatenate in region order (regions are
                              ordered contiguous ranges, so concat IS the
                              merge).  2PC commits the PRIMARY key's
                              region first — the Percolator commit point
                              stays a single region-local WAL append.
- :func:`verify_region_invariants` — drain-time checks the chaos harness
                              asserts: no orphaned region lease, blob
                              MANIFESTs match their sealed segments.

Failover: leases expire after ``lease_timeout_s`` without a heartbeat.
A survivor calls :meth:`RegionStore.failover_expired`, which claims the
expired lease (bumping the epoch), restores checkpoint + tail from the
blob store, replays per Percolator semantics (deferred cross-region
orphan resolution: the merged disposition map finds a secondary's commit
point in the primary's region log), and resumes serving.  The dead
host's stale appender cannot write into the failed-over region: its
epoch no longer matches, so the fence check inside WAL.append raises.
"""

from __future__ import annotations

import json
import logging
import struct
import threading
import zlib
from bisect import bisect_right

from ..kv import wal as wal_mod
from ..kv.shared_store import DurableMVCCStore, SegmentTSOracle
from ..session import tracing
from ..utils.backoff import LeaseExpiredError

log = logging.getLogger("tidb_tpu.fabric.region")

_FHDR = struct.Struct("<8sQ")  # mirrors kv/wal._FHDR (magic, lsn)


class RegionEpochError(LeaseExpiredError):
    """A region operation carried a stale epoch: the region was failed
    over (or released) since this handle claimed it.  Subclasses
    LeaseExpiredError so Backoffer classifies it "lease" and 2PC aborts
    cleanly instead of retrying into a fenced-off log."""


# ---------------------------------------------------------------------------
# keyspace partitioning


class RegionMap:
    """Static partition of the keyspace into ``n`` contiguous regions by
    the first 8 key bytes (big-endian, zero-padded).  Static split is
    the honest scope here — dynamic region splitting/merging is PD's
    job and stays on the roadmap."""

    def __init__(self, n_regions: int):
        if n_regions < 1:
            raise ValueError(f"need >=1 region, got {n_regions}")
        self.n = int(n_regions)
        #: boundary i = first 64-bit key value of region i
        self._bounds = [(i << 64) // self.n for i in range(self.n + 1)]

    def region_of(self, key: bytes) -> int:
        val = int.from_bytes(key[:8].ljust(8, b"\0"), "big")
        rid = bisect_right(self._bounds, val) - 1
        return min(max(rid, 0), self.n - 1)

    def bounds(self, rid: int) -> "tuple[bytes, bytes]":
        """(start_key, end_key) of region ``rid``; b"" means open."""
        if not 0 <= rid < self.n:
            raise IndexError(f"region {rid} out of range 0..{self.n - 1}")
        start = b"" if rid == 0 else self._bounds[rid].to_bytes(8, "big")
        end = (b"" if rid == self.n - 1
               else self._bounds[rid + 1].to_bytes(8, "big"))
        return (start, end)

    def split_range(self, start: bytes, end: bytes) -> list:
        """Intersect [start, end) (end=b"" = +inf) with the region grid:
        -> [(rid, span_start, span_end)] in region (= key) order.  The
        scan fan-out plan: each span goes to its region's store and the
        results concatenate ordered."""
        out = []
        first = self.region_of(start) if start else 0
        last = (self.n - 1 if not end
                else self.region_of(end[:8].ljust(8, b"\0")))
        for rid in range(first, last + 1):
            rs, re_ = self.bounds(rid)
            s = max(start, rs) if rs else start
            e = re_ if not end else (min(end, re_) if re_ else end)
            if e and s >= e:
                continue
            out.append((rid, s, e))
        return out


# ---------------------------------------------------------------------------
# epoch-fenced coordinator facade


class RegionCoordView:
    """What one region's WAL + engine see as "the coordinator": the
    shared cells (TSO, lock table, schema, liveness) pass through to
    the real coordinator; the WAL-frontier cells (wal_len /
    wal_applied) re-target the region's OWN segment cells, and every
    write through them is epoch-fenced.  A zombie host still holding a
    failed-over region's store finds every append rejected here."""

    def __init__(self, coord, rid: int, epoch: int):
        self._c = coord
        self.rid = int(rid)
        self.epoch = int(epoch)

    # -- region-scoped WAL frontier (epoch-fenced writes) -------------------

    def wal_len(self) -> int:
        return self._c.region_committed_len(self.rid)

    def set_wal_len(self, n: int):
        if not self._c.region_set_committed(self.rid, self.epoch, n):
            raise RegionEpochError(
                f"region {self.rid} epoch {self.epoch} fenced: "
                "committed-length write rejected (failed over?)")

    def set_wal_applied(self, slot: int, lsn: int):
        # stale epoch here is not fatal — the failover owner's applied
        # cell is authoritative; a zombie's progress report is ignored
        self._c.region_set_applied(self.rid, self.epoch, lsn)

    def min_wal_applied(self) -> "int | None":
        info = self._c.region_info(self.rid)
        lsn = info.get("applied_lsn", 0)
        return lsn if lsn else None

    def check_fence(self):
        """Raise unless our epoch is still the region's epoch — called
        by WAL.append before any byte hits the log."""
        if not self._c.region_check(self.rid, self.epoch):
            raise RegionEpochError(
                f"region {self.rid} epoch {self.epoch} is stale; "
                "append fenced")

    def set_commit_frontier(self, slot: int, ts: int, lsn: int):
        # region WALs have disjoint LSN spaces, so the slot's single
        # frontier-LSN cell is meaningless across them: publish the ts
        # fence only (lsn stays 0 → readers' LSN wait degenerates to
        # the fast path; cross-region visibility keeps the synchronous
        # catch-up contract, see RegionStore.fresh_read_ts)
        self._c.set_commit_frontier(slot, ts, 0)

    # -- everything else passes through -------------------------------------

    def __getattr__(self, name):
        return getattr(self._c, name)


# ---------------------------------------------------------------------------
# blob replication


class RegionReplicator:
    """Ship (and restore) one region's durability artifacts to/from the
    blob store.  Blob layout per region::

        region-<rid>/MANIFEST                  <- written LAST
        region-<rid>/checkpoint-<lsn>.bin      <- checkpoint.bin verbatim
        region-<rid>/wal-<start>-<end>.bin     <- committed framed tail

    The MANIFEST names exactly the blobs a restore needs plus the tail
    CRC; because blob.put is rename-last AND the MANIFEST is uploaded
    after its blobs, a reader that can fetch a MANIFEST can always fetch
    complete referenced blobs.  Superseded blobs are deleted after the
    new MANIFEST lands (crash between = harmless garbage, swept next
    replicate)."""

    def __init__(self, blob):
        self.blob = blob

    @staticmethod
    def _prefix(rid: int) -> str:
        return f"region-{rid}/"

    def manifest(self, rid: int) -> "dict | None":
        try:
            raw = self.blob.get(self._prefix(rid) + "MANIFEST")
        except Exception:  # noqa: BLE001 — absent or unreadable: no copy
            return None
        return json.loads(raw.decode("utf-8"))

    def replicate(self, rid: int, wal: "wal_mod.WAL", epoch: int) -> dict:
        """Upload checkpoint + committed tail, then the MANIFEST."""
        with tracing.span("region.replicate", region=rid, epoch=epoch):
            return self._replicate(rid, wal, epoch)

    def _replicate(self, rid: int, wal: "wal_mod.WAL", epoch: int) -> dict:
        pre = self._prefix(rid)
        ck_name = None
        ck_lsn = 0
        try:
            with open(wal.ckpt_path, "rb") as f:
                ck = f.read()
        except OSError:
            ck = None
        if ck and len(ck) >= _FHDR.size:
            _magic, ck_lsn = _FHDR.unpack_from(ck, 0)
            ck_name = pre + f"checkpoint-{ck_lsn}.bin"
            self.blob.put(ck_name, ck)
        start, tail = wal.tail_bytes()
        end = start + len(tail)
        tail_name = pre + f"wal-{start}-{end}.bin"
        self.blob.put(tail_name, tail)
        man = {"region": rid, "epoch": epoch, "committed_len": end,
               "base_lsn": start, "checkpoint": ck_name,
               "checkpoint_lsn": ck_lsn, "tail": tail_name,
               "tail_crc": zlib.crc32(tail)}
        self.blob.put(pre + "MANIFEST",
                      json.dumps(man, sort_keys=True).encode("utf-8"))
        keep = {pre + "MANIFEST", ck_name, tail_name}
        for name in self.blob.list(pre):
            if name not in keep:
                self.blob.delete(name)
        return man

    def restore(self, rid: int, dest_dir: str) -> dict:
        """Materialize a WAL directory from the region's blobs.  Raises
        (from blob.get / the CRC check) rather than restoring a torn
        copy — recovery must never replay a log it cannot trust."""
        from .blob import BlobError
        with tracing.span("region.restore", region=rid):
            man = self.manifest(rid)
            if man is None:
                raise BlobError(f"region {rid}: no MANIFEST in blob store")
            ck = (self.blob.get(man["checkpoint"])
                  if man["checkpoint"] else None)
            tail = self.blob.get(man["tail"]) if man["tail"] else b""
            if zlib.crc32(tail) != man["tail_crc"]:
                raise BlobError(
                    f"region {rid}: tail CRC mismatch "
                    f"(manifest {man['tail_crc']}, blob {zlib.crc32(tail)})")
            tracing.event("region.restore.blobs", epoch=man["epoch"],
                          bytes=len(tail) + (len(ck) if ck else 0))
            wal_mod.write_wal_files(dest_dir, man["base_lsn"], tail,
                                    checkpoint=ck)
            return man


# ---------------------------------------------------------------------------
# the router


class RegionStore:
    """One DurableMVCCStore per owned region behind the single-engine
    surface kv/store.Storage expects.  See the module docstring for the
    routing rules; every region store shares ONE SegmentTSOracle so
    commit timestamps stay fleet-monotonic across regions."""

    def __init__(self, root: str, coordinator, slot: int, *,
                 blob=None, n_regions: "int | None" = None,
                 lease_timeout_s: float = 2.0):
        n = n_regions if n_regions is not None else coordinator.nregions
        self.region_map = RegionMap(n)
        self.root = root
        self.coord = coordinator
        self.slot = int(slot)
        self.blob = blob
        self.lease_timeout_s = float(lease_timeout_s)
        self.tso = SegmentTSOracle(coordinator)
        self.stores: dict[int, DurableMVCCStore] = {}
        self.epochs: dict[int, int] = {}
        self.safe_point = 0
        self._mu = threading.RLock()
        self._replicator = RegionReplicator(blob) if blob is not None else None

    # -- lifecycle -----------------------------------------------------------

    def open_regions(self, rids=None, *, restore: bool = False) -> list:
        """Claim + open the given regions (default: all).  Returns the
        region ids actually claimed — a region whose lease another host
        holds is skipped, not fought over."""
        want = list(rids) if rids is not None else list(
            range(self.region_map.n))
        claimed = []
        with self._mu:
            for rid in want:
                if rid in self.stores:
                    claimed.append(rid)
                    continue
                if self._open_one(rid, restore=restore):
                    claimed.append(rid)
            self._resolve_cross_region()
        return claimed

    def _open_one(self, rid: int, *, restore: bool) -> bool:
        with tracing.span("region.claim", region=rid, slot=self.slot):
            epoch = self.coord.region_claim(rid, self.slot,
                                            self.lease_timeout_s)
            if not epoch:
                return False  # a live foreign lease — not ours to take
            rdir = wal_mod.region_dir(self.root, rid)
            if restore and self._replicator is not None:
                man = self._replicator.manifest(rid)
                if man is not None:
                    self._replicator.restore(rid, rdir)
            view = RegionCoordView(self.coord, rid, epoch)
            w = wal_mod.WAL(rdir, coordinator=view)
            st = DurableMVCCStore(w, coordinator=view, slot=self.slot,
                                  oracle=self.tso)
            st.recover(defer_orphans=True)
            self.stores[rid] = st
            self.epochs[rid] = epoch
            return True

    def _resolve_cross_region(self):
        """Percolator commit-point resolution across region logs: merge
        every region's replayed disposition so a secondary lock in
        region B finds its primary's commit record from region A.
        ``assume_fenced``: we hold each region's current epoch, so the
        previous owner — dead or a partitioned zombie — can never land
        its commit past the fence; its leftovers are safe to resolve
        even while its slot lease still looks live."""
        merged: dict[int, tuple] = {}
        for st in self.stores.values():
            merged.update(st._recover_disposition)
        total = 0
        for st in self.stores.values():
            total += st.resolve_orphans(merged, st._recover_lock_owner,
                                        assume_fenced=True)
        if total:
            log.info("resolved %d cross-region orphan locks", total)
        return total

    def heartbeat(self) -> list:
        """Renew every owned lease.  Returns region ids LOST (heartbeat
        rejected: failed over behind us) — those stores are closed and
        dropped, so later routing raises instead of serving stale."""
        lost = []
        with self._mu:
            for rid in list(self.stores):
                ok = False
                try:
                    ok = self.coord.region_heartbeat(rid, self.slot,
                                                     self.epochs[rid])
                except Exception as e:  # noqa: BLE001 — segment gone at
                    #   teardown: treat as lost, close locally
                    log.debug("region %d heartbeat failed: %s", rid, e)
                if not ok:
                    lost.append(rid)
                    self._drop(rid)
        if lost:
            log.warning("slot %d lost regions %s (failed over)",
                        self.slot, lost)
        return lost

    def failover_expired(self) -> list:
        """Claim + restore every region whose lease expired — the
        survivor half of host-loss recovery.  Restores from the blob
        store (checkpoint + tail), replays, resolves orphans against
        the merged disposition map, resumes serving."""
        took = []
        with tracing.span("region.failover", slot=self.slot), self._mu:
            for rid in self.coord.regions_expired(self.lease_timeout_s):
                if rid in self.stores:
                    continue
                if self._open_one(rid, restore=True):
                    took.append(rid)
            if took:
                self._resolve_cross_region()
        if took:
            log.warning("slot %d failed over regions %s", self.slot, took)
        return took

    def replicate(self, rids=None) -> dict:
        """Ship checkpoint + committed tail of the given (default: all
        owned) regions to the blob store.  -> {rid: manifest}."""
        if self._replicator is None:
            return {}
        out = {}
        with self._mu:
            targets = list(rids) if rids is not None else list(self.stores)
            for rid in targets:
                st = self.stores[rid]
                epoch = self.epochs[rid]
                try:
                    if not self.coord.region_check(rid, epoch):
                        # failed over behind us: the new owner's replica
                        # is authoritative — a zombie's close-time
                        # replicate must never clobber its MANIFEST
                        continue
                except Exception as e:  # noqa: BLE001
                    log.debug("region %d epoch check unavailable at "
                              "replicate, skipping: %s", rid, e)
                    continue
                out[rid] = self._replicator.replicate(rid, st.wal, epoch)
        return out

    def checkpoint_region(self, rid: int) -> int:
        with self._mu:
            st = self.stores[rid]
            return st.wal.checkpoint(st.dump_state())

    def close(self, *, replicate: bool = True):
        with self._mu:
            if replicate and self._replicator is not None:
                try:
                    self.replicate()
                except Exception as e:  # noqa: BLE001 — best-effort on
                    #   shutdown; the WAL itself is the durable copy
                    log.warning("close-time replicate failed: %s", e)
            for rid in list(self.stores):
                self._drop(rid, release=True)

    def _drop(self, rid: int, *, release: bool = False):
        st = self.stores.pop(rid, None)
        epoch = self.epochs.pop(rid, None)
        if st is not None:
            try:
                st.close()
            except Exception as e:  # noqa: BLE001
                log.debug("region %d close failed: %s", rid, e)
        if release and epoch is not None:
            try:
                self.coord.region_release(rid, self.slot)
            except Exception as e:  # noqa: BLE001 — segment may be gone
                log.debug("region %d release failed: %s", rid, e)

    # -- routing helpers -----------------------------------------------------

    def _store_for(self, key: bytes) -> DurableMVCCStore:
        rid = self.region_map.region_of(key)
        st = self.stores.get(rid)
        if st is None:
            raise RegionEpochError(
                f"region {rid} not owned by slot {self.slot} "
                f"(owner: {self.coord.region_owners().get(rid)})")
        return st

    def _group(self, keys) -> "dict[int, list]":
        groups: dict[int, list] = {}
        for k in keys:
            groups.setdefault(self.region_map.region_of(k), []).append(k)
        return groups

    def owned_regions(self) -> list:
        with self._mu:
            return sorted(self.stores)

    # -- engine surface (what kv/store.Storage calls) ------------------------

    def get(self, key: bytes, ts: int, own_start_ts: int = 0):
        return self._store_for(key).get(key, ts, own_start_ts=own_start_ts)

    def scan(self, start: bytes, end: bytes, ts: int, limit: int = 0,
             own_start_ts: int = 0):
        out = []
        for rid, s, e in self.region_map.split_range(start, end):
            st = self.stores.get(rid)
            if st is None:
                raise RegionEpochError(
                    f"scan spans unowned region {rid}")
            # regions are ordered contiguous ranges: concatenating the
            # per-region results in rid order IS the ordered merge
            rem = limit - len(out) if limit else 0
            out.extend(st.scan(s, e, ts, limit=rem,
                               own_start_ts=own_start_ts))
            if limit and len(out) >= limit:
                return out[:limit]
        return out

    def prewrite(self, mutations, primary: bytes, start_ts: int,
                 view_seq: "int | None" = None):
        # view_seq is accepted but not forwarded: the anchor is a
        # per-store scalar and region WALs apply independently, so a
        # single sequence cannot cover a multi-region write set.  The
        # region view (RegionStore has no read_view_seq) always hands
        # writers None — region-mode conflict detection stays on the
        # ts comparison it had before the anchor existed.
        groups: dict[int, list] = {}
        for m in mutations:
            groups.setdefault(self.region_map.region_of(m[0]),
                              []).append(m)
        done = []
        try:
            for rid in sorted(groups):
                # every group carries the same primary: orphan
                # resolution resolves secondaries via the primary's
                # region log, whatever region they live in
                self._require(rid).prewrite(groups[rid], primary, start_ts)
                done.append(rid)
        except BaseException:
            for rid in done:
                try:
                    self.stores[rid].rollback(
                        [m[0] for m in groups[rid]], start_ts)
                except Exception as e:  # noqa: BLE001 — best effort;
                    #   leftover locks resolve via the primary later
                    log.debug("prewrite unwind region %d: %s", rid, e)
            raise
        return None

    def commit(self, keys, start_ts: int, commit_ts: int):
        groups = self._group(keys)
        primary_rid = self.region_map.region_of(keys[0])
        order = [primary_rid] + [r for r in sorted(groups)
                                 if r != primary_rid]
        for rid in order:
            # the primary's region commits FIRST: its WAL append is the
            # txn's Percolator commit point; a crash after it resolves
            # every secondary as committed, a crash before rolls back
            self._require(rid).commit(groups[rid], start_ts, commit_ts)

    def rollback(self, keys, start_ts: int):
        for rid, ks in self._group(keys).items():
            self._require(rid).rollback(ks, start_ts)

    def acquire_pessimistic_lock(self, keys, primary: bytes,
                                 start_ts: int, for_update_ts: int,
                                 view_seq: "int | None" = None):
        # view_seq unused for the same reason as in prewrite
        for rid, ks in sorted(self._group(keys).items()):
            self._require(rid).acquire_pessimistic_lock(
                ks, primary, start_ts, for_update_ts)

    def resolve_lock(self, key: bytes, committed: bool, commit_ts: int = 0):
        return self._store_for(key).resolve_lock(key, committed, commit_ts)

    def clear_wait(self, start_ts: int):
        for st in self.stores.values():
            st.clear_wait(start_ts)

    def bump_table_version(self, table_id: int, commit_ts: int = 0) -> int:
        out = 0
        for st in self.stores.values():
            out = max(out, st.bump_table_version(table_id, commit_ts))
        return out

    def raw_put(self, key: bytes, value: bytes, commit_ts=None):
        return self._store_for(key).raw_put(key, value, commit_ts)

    def raw_batch_put(self, pairs, commit_ts=None):
        groups: dict[int, list] = {}
        for k, v in pairs:
            groups.setdefault(self.region_map.region_of(k),
                              []).append((k, v))
        for rid in sorted(groups):
            self._require(rid).raw_batch_put(groups[rid], commit_ts)

    def raw_delete_range(self, start: bytes, end: bytes):
        for rid, s, e in self.region_map.split_range(start, end):
            self._require(rid).raw_delete_range(s, e)

    def gc(self, safe_point: int):
        self.safe_point = safe_point
        removed = 0
        for st in self.stores.values():
            st.safe_point = safe_point
            removed += st.gc(safe_point)
        return removed

    def catch_up(self):
        for st in list(self.stores.values()):
            st.catch_up()

    def fresh_read_ts(self) -> int:
        """Region-fleet ts fence: order every new snapshot ABOVE every
        live peer's acked durable commit_ts (the frontier cells carry
        ts only here — RegionCoordView publishes lsn=0 because region
        WAL LSN spaces are disjoint).  Visibility of those commits
        rides the synchronous per-region catch_up Storage.begin already
        performs."""
        try:
            fronts = self.coord.commit_frontiers()
        except Exception as e:  # noqa: BLE001 — segment gone at
            #   teardown / coordinator down-window: plain monotonic ts
            log.debug("commit_frontiers unreadable (%s); plain ts", e)
            fronts = {}
        need = max((fts for s, (fts, _lsn) in fronts.items()
                    if s != self.slot), default=0)
        if need:
            self.tso.advance_to(need)
        return self.tso.next_ts()

    def publish_frontier(self):
        """Heartbeat republish funnel (fabric/worker.py): forward to
        every owned region's store."""
        for st in list(self.stores.values()):
            st.publish_frontier()

    def _require(self, rid: int) -> DurableMVCCStore:
        st = self.stores.get(rid)
        if st is None:
            raise RegionEpochError(
                f"region {rid} not owned by slot {self.slot}")
        return st

    def wal_status(self) -> dict:
        with self._mu:
            return {rid: st.wal_status() for rid, st in self.stores.items()}


# ---------------------------------------------------------------------------
# drain-time invariants (chaos harness, satellite 6)


def verify_region_invariants(coordinator, blob=None) -> dict:
    """Region-fleet drain checks, asserted at the end of both chaos
    modes: (a) no region lease survives a drained fleet — an orphaned
    lease means some close/release path leaked; (b) every blob MANIFEST
    names blobs that exist with exactly the sealed length + CRC it
    recorded — a mismatch means replication published a manifest its
    blobs do not back."""
    snap = coordinator.snapshot()
    region_leases = [r["region"] for r in snap.get("regions", [])
                     if r["owner"] >= 0]
    manifest_errors = []
    if blob is not None:
        for name in blob.list():
            if not name.endswith("/MANIFEST"):
                continue
            try:
                man = json.loads(blob.get(name).decode("utf-8"))
            except Exception as e:  # noqa: BLE001
                manifest_errors.append(f"{name}: unreadable ({e})")
                continue
            for ref in (man.get("checkpoint"), man.get("tail")):
                if ref and not blob.exists(ref):
                    manifest_errors.append(f"{name}: missing blob {ref}")
            if man.get("tail"):
                try:
                    tail = blob.get(man["tail"])
                except Exception as e:  # noqa: BLE001
                    if blob.exists(man["tail"]):
                        manifest_errors.append(
                            f"{name}: tail unreadable ({e})")
                    continue  # absent already reported above
                want_len = man["committed_len"] - man["base_lsn"]
                if len(tail) != want_len:
                    manifest_errors.append(
                        f"{name}: tail length {len(tail)} != sealed "
                        f"{want_len}")
                elif zlib.crc32(tail) != man["tail_crc"]:
                    manifest_errors.append(f"{name}: tail CRC mismatch")
    ok = not region_leases and not manifest_errors
    return {"ok": ok, "region_leases": region_leases,
            "manifest_errors": manifest_errors}
