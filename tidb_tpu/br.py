"""Backup / restore + logical dump + checkpointed import — the BR,
Dumpling and Lightning roles (reference: br/pkg/task/backup.go:221,
restore.go:216, dumpling/export/dump.go, br/pkg/lightning/checkpoints/,
br/pkg/lightning/errormanager/ duplicate detection).

All file IO routes through the external-storage abstraction
(br_storage.py — the br/pkg/storage role): a backup written to
``local://`` restores from ``memory://`` and vice versa, and a cloud
backend is one ExternalStorage subclass away.

Backup format (one prefix per run):
    backupmeta.json                 run metadata + per-table stats
    {db}.{table}.schema.json       TableInfo (exact catalog state)
    {db}.{table}.data.jsonl        rows as {"h": handle, "v": hex(rowcodec)}
Row payloads reuse the engine's row codec, so restore is bit-exact —
decimals, dates and binary collations round-trip without re-parsing.

Dump format (mydumper-style, reference dumpling/export):
    {db}.{table}-schema.sql        CREATE TABLE
    {db}.{table}.sql | .csv        INSERT statements / CSV rows

Import reads a dump with a progress checkpoint (_import_checkpoint.json)
updated after every committed batch: a crashed import resumes at the
first unfinished table/offset instead of redoing or duplicating work
(reference: lightning checkpoints). `workers` > 1 imports tables in
parallel on their own sessions (lightning's table-concurrency);
`on_duplicate="record"` logs conflicting rows to _import_conflicts.jsonl
and continues (lightning's errormanager) instead of failing the run.
"""

from __future__ import annotations

import io
import json
import threading
import time

from . import tablecodec
from .br_storage import open_storage
from .errors import TiDBError
from .model import TableInfo
from .table import Table

BATCH = 2048


# -- backup (reference: br/pkg/task/backup.go) -------------------------------

def backup_database(session, db_name: str, dest: str) -> dict:
    infos = session.infoschema()
    if infos.schema_by_name(db_name) is None:
        raise TiDBError(f"Unknown database '{db_name}'")
    st = open_storage(dest)
    txn = session.store.begin()  # one snapshot: a consistent backup
    coord = getattr(session.domain, "coordinator", None)
    # one pin PER RUN (keyed by snapshot ts): concurrent backups must not
    # raise or clear each other's GC floor — set_safepoint only moves
    # forward and clear would drop a foreign pin (reference: BR registers
    # a unique service safepoint id per task, br/pkg/task/backup.go)
    pin_key = f"br-{txn.start_ts}"
    if coord is not None:
        coord.set_safepoint(pin_key, txn.start_ts)
    meta = {"db": db_name, "ts": txn.start_ts,
            "created": time.strftime("%Y-%m-%d %H:%M:%S"), "tables": []}
    try:
        for info in infos.tables_in_schema(db_name):
            base = f"{db_name}.{info.name}"
            payload = info.to_json()
            st.write_text(base + ".schema.json",
                          payload if isinstance(payload, str)
                          else json.dumps(payload))
            n = 0
            phys_ids = [info.id]
            if info.partition is not None:
                # rows live under partition physical ids; restore re-routes
                # by value so the dump is just (handle, row) pairs
                phys_ids = [d.id for d in info.partition.defs]
            with st.open_write(base + ".data.jsonl") as f:
                for pid in phys_ids:
                    rec_end = tablecodec.record_prefix(pid) + b"\xff" * 9
                    for key, value in txn.scan(
                            tablecodec.record_prefix(pid), rec_end):
                        _tid, h = tablecodec.decode_record_key(key)
                        f.write(json.dumps(
                            {"h": h, "v": value.hex()}) + "\n")
                        n += 1
            meta["tables"].append({"name": info.name, "rows": n})
        meta["wal"] = _backup_wal_tail(session, st, txn.start_ts)
    finally:
        txn.rollback()
        if coord is not None:
            coord.clear_safepoint(pin_key)
    st.write_text("backupmeta.json", json.dumps(meta, indent=1))
    return meta


def _backup_wal_tail(session, st, backup_ts: int) -> "dict | None":
    """Durable-store half of the backup (kv/wal.py): ship the LAST
    checkpoint (when one exists) plus the log tail since it, filtered
    to records at or below the backup snapshot ts — so a physical
    restore replays to EXACTLY the backup point, commits that raced
    past the snapshot excluded the same way the scan excluded them.
    THEN checkpoint (bounding future recovery; the truncation must not
    eat the tail we just shipped, so ship-first).  None when the store
    is not durable (in-memory deployments carry no wal)."""
    eng = session.store.mvcc
    wal = getattr(eng, "wal", None)
    if wal is None or not hasattr(eng, "dump_state"):
        return None
    import pickle
    from .kv.shared_store import _record_ts
    ck = wal.read_checkpoint()
    from_lsn = wal.base_lsn
    if ck is not None and ck[0] >= wal.base_lsn:
        st.write_file("wal.ckpt.bin", ck[1])
        from_lsn = ck[0]
    tail = [rec for rec, _lsn in wal.read_records(from_lsn)
            if _record_ts(rec) <= backup_ts]
    st.write_file("wal.tail.bin", pickle.dumps(tail, protocol=4))
    ck_lsn = wal.checkpoint(eng.dump_state())
    return {"checkpoint_lsn": ck_lsn, "tail_records": len(tail),
            "has_checkpoint": ck is not None, "backup_ts": backup_ts}


def restore_wal_tail(storage, src: str) -> int:
    """Replay a backup's WAL tail into a DURABLE ``storage``
    (kv.Storage over kv/shared_store.DurableMVCCStore): the
    physical-restore path to the exact backup ts.  Records walk the
    engine's own journal-apply path (prewrite → locks, commit →
    conversion, last-disposition-wins), and the oracle advances past
    the replayed high-water so post-restore snapshots see everything.
    Returns the number of records applied (0 when the backup carried
    no tail or the target store is not durable)."""
    import pickle
    from .kv.shared_store import _record_ts
    st = open_storage(src)
    if not st.exists("wal.tail.bin"):
        return 0
    eng = storage.mvcc
    apply_rec = getattr(eng, "_apply", None)
    if apply_rec is None:
        return 0
    if st.exists("wal.ckpt.bin"):
        eng.load_state(st.read_file("wal.ckpt.bin"))
    records = pickle.loads(st.read_file("wal.tail.bin"))
    max_ts = 0
    for rec in records:
        apply_rec(rec, replay=True)
        max_ts = max(max_ts, _record_ts(rec))
    if max_ts:
        eng.tso.advance_to(max_ts)
    # locks left over are txns that had not committed at backup_ts
    # (their commit record was filtered out): not part of the backup
    with eng._lock:
        leftovers = list(eng.locks.items())
    for key, lk in leftovers:
        from .kv.mvcc import MVCCStore
        MVCCStore.rollback(eng, [key], lk.start_ts)
    # the journal-apply path writes replica state, not the target's own
    # log — checkpoint so the restored state is durable in ONE step
    eng.wal.checkpoint(eng.dump_state())
    return len(records)


# -- physical backup / restore (reference: br/pkg/backup's SST export +
#    br/pkg/lightning/backend/local pebble-SST build-and-ingest). The
#    engine's native on-disk unit is the MVCC KV snapshot itself: backup
#    streams every committed (key, value) under the table prefix —
#    records AND index entries — as length-prefixed binary with a
#    per-file sha256; restore rewrites the 8-byte table/partition id in
#    each key (BR's rewrite rules, br/pkg/restore/util.go) and ingests
#    via raw_batch_put, bypassing SQL, rowcodec decode and index
#    rebuild entirely. ----------------------------------------------------

def physical_backup_database(session, db_name: str, dest: str) -> dict:
    import hashlib
    import struct
    infos = session.infoschema()
    if infos.schema_by_name(db_name) is None:
        raise TiDBError(f"Unknown database '{db_name}'")
    st = open_storage(dest)
    txn = session.store.begin()
    coord = getattr(session.domain, "coordinator", None)
    pin_key = f"br-{txn.start_ts}"
    if coord is not None:
        coord.set_safepoint(pin_key, txn.start_ts)
    meta = {"db": db_name, "ts": txn.start_ts, "mode": "physical",
            "created": time.strftime("%Y-%m-%d %H:%M:%S"), "tables": []}
    try:
        for info in infos.tables_in_schema(db_name):
            base = f"{db_name}.{info.name}"
            payload = info.to_json()
            st.write_text(base + ".schema.json",
                          payload if isinstance(payload, str)
                          else json.dumps(payload))
            ids = [info.id]
            if info.partition is not None:
                ids += [d.id for d in info.partition.defs]
            n = 0
            n_rows = 0
            sha = hashlib.sha256()
            nbytes = 0
            with st.open_write_bytes(base + ".kv.bin") as f:
                for pid in sorted(set(ids)):
                    # the full physical-id prefix covers record AND index
                    # keyspaces in one ordered scan
                    p = tablecodec.TABLE_PREFIX + tablecodec._enc_i64(pid)
                    for key, value in txn.scan(p, p + b"\xff" * 24):
                        rec = struct.pack("<II", len(key), len(value))
                        f.write(rec)
                        f.write(key)
                        f.write(value)
                        sha.update(rec)
                        sha.update(key)
                        sha.update(value)
                        nbytes += 8 + len(key) + len(value)
                        n += 1
                        if key[9:11] == tablecodec.RECORD_SEP:
                            n_rows += 1
            meta["tables"].append({"name": info.name, "rows": n_rows,
                                   "kv": n, "bytes": nbytes,
                                   "sha256": sha.hexdigest(),
                                   "ids": sorted(set(ids))})
    finally:
        txn.rollback()
        if coord is not None:
            coord.clear_safepoint(pin_key)
    st.write_text("backupmeta.json", json.dumps(meta, indent=1))
    return meta


#: keys ingested per raw_batch_put call (bounds peak batch memory)
_INGEST_BATCH = 4096


def physical_restore_database(session, src: str,
                              db_name: str | None = None,
                              meta: dict | None = None) -> dict:
    import hashlib
    import struct
    st = open_storage(src)
    if meta is None:  # the session layer passes its already-parsed copy
        meta = json.loads(st.read_text("backupmeta.json"))
    if meta.get("mode") != "physical":
        raise TiDBError("backup at this path is not a physical backup")
    target_db = db_name or meta["db"]
    if session.infoschema().schema_by_name(target_db) is None:
        session.execute(f"create database `{target_db}`")
    mvcc = session.store.mvcc
    restored = []
    for t in meta["tables"]:
        base = f"{meta['db']}.{t['name']}"
        raw = st.read_text(base + ".schema.json")
        info = TableInfo.from_json(json.loads(raw)
                                   if raw.lstrip().startswith("{")
                                   else raw)
        if session.infoschema().has_table(target_db, info.name):
            raise TiDBError(f"table '{target_db}.{info.name}' already "
                            f"exists; drop it before RESTORE")
        # pass 1 — verify the stream checksum BEFORE any ingest: corrupt
        # data must never become readable, even transiently (reference:
        # BR validates SST checksums before ingest)
        sha = hashlib.sha256()
        with st.open_read_bytes(base + ".kv.bin") as f:
            while True:
                blk = f.read(1 << 20)
                if not blk:
                    break
                sha.update(blk)
        if sha.hexdigest() != t["sha256"]:
            raise TiDBError(f"checksum mismatch restoring {base}: "
                            f"backup is corrupt")
        _create_from_info(session, target_db, info)
        new_info = session.infoschema().table_by_name(target_db, info.name)
        # rewrite rules: source physical id -> restored physical id
        # (partition defs keep their order through the catalog round-trip)
        id_map = {info.id: new_info.id}
        if info.partition is not None:
            for od, nd in zip(info.partition.defs,
                              new_info.partition.defs):
                id_map[od.id] = nd.id
        commit_ts = session.store.next_ts()
        n = 0
        batch = []
        try:
            with st.open_read_bytes(base + ".kv.bin") as f:
                while True:
                    hdr = f.read(8)
                    if not hdr:
                        break
                    klen, vlen = struct.unpack("<II", hdr)
                    key = f.read(klen)
                    value = f.read(vlen)
                    if len(key) != klen or len(value) != vlen:
                        raise TiDBError(
                            f"truncated kv stream in {base}.kv.bin")
                    old_id = tablecodec._dec_i64(key[1:9])
                    new_id = id_map.get(old_id)
                    if new_id is None:
                        raise TiDBError(f"kv key for unknown physical id "
                                        f"{old_id} in {base}.kv.bin")
                    batch.append((tablecodec.TABLE_PREFIX
                                  + tablecodec._enc_i64(new_id) + key[9:],
                                  value))
                    if key[9:11] == tablecodec.RECORD_SEP:
                        n += 1
                    if len(batch) >= _INGEST_BATCH:
                        mvcc.raw_batch_put(batch, commit_ts)
                        batch = []
            if batch:
                mvcc.raw_batch_put(batch, commit_ts)
        except Exception:
            # sweep ingested versions AND the table the failed restore
            # itself created, so a retry isn't blocked by 'already
            # exists' (reference: restore rolls back downloaded-SST
            # state)
            for nid in id_map.values():
                p = tablecodec.TABLE_PREFIX + tablecodec._enc_i64(nid)
                mvcc.raw_delete_range(p, p + b"\xff" * 24)
            try:
                session.execute(
                    f"drop table `{target_db}`.`{info.name}`")
            except Exception:
                pass  # surfacing the original failure matters more
            raise
        mvcc.bump_table_version(new_info.id, commit_ts)
        restored.append({"name": info.name, "rows": n})
    return {"db": target_db, "tables": restored, "mode": "physical"}


# -- restore (reference: br/pkg/task/restore.go) -----------------------------

def restore_database(session, src: str, db_name: str | None = None,
                     meta: dict | None = None) -> dict:
    # restore into a running FLEET propagates by construction: every
    # _create_from_info commit bumps the meta schema version, which the
    # durable store publishes to the segment's schema-version cell —
    # sibling workers' schema leases reload and their replicas tail the
    # restored rows (kv/shared_store.py)
    st = open_storage(src)
    if meta is None:  # the session layer passes its already-parsed copy
        meta = json.loads(st.read_text("backupmeta.json"))
    target_db = db_name or meta["db"]
    if session.infoschema().schema_by_name(target_db) is None:
        session.execute(f"create database `{target_db}`")
    restored = []
    for t in meta["tables"]:
        base = f"{meta['db']}.{t['name']}"
        raw = st.read_text(base + ".schema.json")
        info = TableInfo.from_json(json.loads(raw)
                                   if raw.lstrip().startswith("{")
                                   else raw)
        if session.infoschema().has_table(target_db, info.name):
            raise TiDBError(f"table '{target_db}.{info.name}' already "
                            f"exists; drop it before RESTORE")
        _create_from_info(session, target_db, info)
        new_info = session.infoschema().table_by_name(target_db, info.name)
        with st.open_read(base + ".data.jsonl") as f:
            n = _restore_rows(session, new_info, f)
        restored.append({"name": info.name, "rows": n})
    return {"db": target_db, "tables": restored}


def _create_from_info(session, db_name: str, info: TableInfo):
    """Recreate the table from the backed-up TableInfo via the catalog
    (new table id; column/index ids preserved from the source)."""
    from .meta import Meta
    with session.domain.ddl_lock:
        txn = session.store.begin()
        try:
            m = Meta(txn)
            db = next(d for d in m.list_databases()
                      if d.name.lower() == db_name.lower())
            clone = TableInfo.from_json(info.to_json())
            clone.id = m.gen_global_id()
            if clone.partition is not None:
                # fresh physical ids: the source table may still exist
                for d in clone.partition.defs:
                    d.id = m.gen_global_id()
            m.create_table(db.id, clone)
            m.bump_schema_version()
            txn.commit()
        except Exception:
            txn.rollback()
            raise
    session.domain.reload_schema()


def _restore_rows(session, info: TableInfo, lines) -> int:
    n = 0
    batch = []
    for line in lines:
        if not line.strip():
            continue
        rec = json.loads(line)
        batch.append((rec["h"], bytes.fromhex(rec["v"])))
        if len(batch) >= BATCH:
            _write_batch(session, info, batch)
            n += len(batch)
            batch = []
    if batch:
        _write_batch(session, info, batch)
        n += len(batch)
    return n


def _write_batch(session, info, batch):
    txn = session.store.begin()
    try:
        tbl = Table(info, txn)
        for handle, value in batch:
            row = tablecodec.decode_row(value)
            tbl.add_record(row, handle, check_dup=False)
        txn.commit()
    except Exception:
        txn.rollback()
        raise
    session.domain.columnar_cache.invalidate(info.id)


# -- logical dump (reference: dumpling/export/dump.go) ------------------------

def dump_database(session, db_name: str, dest: str, fmt: str = "sql",
                  consistency: str = "snapshot") -> dict:
    """Logical dump (the dumpling role). consistency modes (reference:
    dumpling/export/consistency.go):
    - 'snapshot' (default): every table's data SELECT runs at ONE
      historical read ts (the engine's tidb_snapshot stale-read view) —
      writes landing mid-dump are invisible, the dump is transactionally
      consistent across tables;
    - 'none': each table reads at its own statement snapshot (fastest,
      per-table consistent only)."""
    if fmt not in ("sql", "csv"):
        raise TiDBError("dump format must be 'sql' or 'csv'")
    if consistency not in ("snapshot", "none"):
        raise TiDBError("dump consistency must be 'snapshot' or 'none'")
    infos = session.infoschema()
    if infos.schema_by_name(db_name) is None:
        raise TiDBError(f"Unknown database '{db_name}'")
    st = open_storage(dest)
    snap_ts = None
    prev_snap = None
    pin_key = None
    if consistency == "snapshot":
        snap_ts = session.execute("select now(6)")[-1].rows[0][0]
        prev_snap = session.get_sysvar("tidb_snapshot")
        session.execute(f"set tidb_snapshot = '{snap_ts}'")
        # pin the GC safepoint like backup_database: the stale read holds
        # no live txn, so without the pin GC could prune the dump's read
        # view mid-run (error 9006 partway through)
        read_ts = session.stale_read_ts()
        coord = session.domain.coordinator
        pin_key = f"dump-{read_ts}"
        coord.set_safepoint(pin_key, read_ts)
    out = {"db": db_name, "tables": [], "consistency": consistency,
           "snapshot": snap_ts}
    # base tables first, then views in dependency order, so view DDL
    # (which plans its select) can resolve its sources on import; views
    # carry schema only, never INSERT data
    all_infos = _dump_order(infos.tables_in_schema(db_name))
    try:
        _dump_tables(session, st, db_name, all_infos, fmt, out)
    finally:
        if snap_ts is not None:
            # restore the CALLER's view, not '' — the session may itself
            # be inside an explicit stale-read window
            session.set_sysvar("tidb_snapshot", prev_snap or "")
        if pin_key is not None:
            session.domain.coordinator.clear_safepoint(pin_key)
    st.write_text("metadata.json", json.dumps(out, indent=1))
    return out


def _dump_tables(session, st, db_name, all_infos, fmt, out):
    for info in all_infos:
        base = f"{db_name}.{info.name}"
        create = session.execute(
            f"show create table `{db_name}`.`{info.name}`")[-1].rows[0][1]
        st.write_text(base + "-schema.sql", create + ";\n")
        if info.is_view:
            out["tables"].append({"name": info.name, "rows": 0,
                                  "is_view": True})
            continue
        res = session.execute(
            f"select * from `{db_name}`.`{info.name}`")[-1]
        rows = res.rows  # display strings (None = NULL)
        if fmt == "sql":
            with st.open_write(base + ".sql") as f:
                for i in range(0, len(rows), 256):
                    chunk = rows[i:i + 256]
                    vals = ",\n".join(
                        "(" + ", ".join(_sql_lit(v) for v in r) + ")"
                        for r in chunk)
                    f.write(f"INSERT INTO `{info.name}` VALUES\n{vals};\n")
        else:
            import csv
            with st.open_write(base + ".csv") as f:
                w = csv.writer(f)
                w.writerow(res.names)
                for r in rows:
                    # NULL sentinel is \N; a LITERAL leading backslash is
                    # escaped by doubling so the reader can tell them
                    # apart (mydumper-style)
                    w.writerow([
                        "\\N" if v is None
                        else ("\\" + v if isinstance(v, str)
                              and v.startswith("\\") else v)
                        for v in r])
        out["tables"].append({"name": info.name, "rows": len(rows)})


def _dump_order(tables):
    """Base tables (by name), then views topologically sorted so every view
    precedes views defined over it (cycles fall back to name order)."""
    base = sorted((t for t in tables if not t.is_view), key=lambda t: t.name)
    views = sorted((t for t in tables if t.is_view), key=lambda t: t.name)
    by_name = {v.name.lower(): v for v in views}
    deps = {}
    for v in views:
        names = set()
        try:
            from .parser import parse
            from .priv_check import _collect_tables
            tabs = []
            _collect_tables(parse(v.view["select"])[0], tabs)
            names = {tn.name.lower() for tn in tabs if tn.name.lower()
                     in by_name and tn.name.lower() != v.name.lower()}
        except Exception:
            pass
        deps[v.name.lower()] = names
    ordered, done = [], set()

    def visit(name, seen):
        if name in done or name in seen:
            return
        seen.add(name)
        for d in sorted(deps.get(name, ())):
            visit(d, seen)
        done.add(name)
        ordered.append(by_name[name])
    for v in views:
        visit(v.name.lower(), set())
    return base + ordered


_NUMERIC_RE = None


def _sql_lit(v) -> str:
    if v is None:
        return "NULL"
    global _NUMERIC_RE
    if _NUMERIC_RE is None:
        import re
        # canonical numerics only: a float() probe would unquote 'nan',
        # '12_3' (python underscore literals) and strip '0010' — display
        # values of NUMERIC columns always match this shape, so anything
        # else is string data and must be quoted
        _NUMERIC_RE = re.compile(r"-?(0|[1-9]\d*)(\.\d+)?([eE][+-]?\d+)?$")
    s = str(v)
    if _NUMERIC_RE.fullmatch(s):
        return s
    # newlines must be escaped or the ';\n' statement splitter would break
    s = (s.replace("\\", "\\\\").replace("'", "\\'")
         .replace("\n", "\\n").replace("\r", "\\r"))
    return "'" + s + "'"


def _str_lit(s: str) -> str:
    """Always-quoted literal: CSV fields are untyped strings; the INSERT
    cast converts them into numeric/date columns, so quoting everything is
    both safe and type-faithful."""
    s = (s.replace("\\", "\\\\").replace("'", "\\'")
         .replace("\n", "\\n").replace("\r", "\\r"))
    return "'" + s + "'"


# -- import with checkpoint/resume (reference: lightning checkpoints) ---------

class _ImportState:
    """Shared, locked import progress: checkpoint + conflict log."""

    def __init__(self, st):
        self.st = st
        self.mu = threading.Lock()
        self.ckpt = {"done_tables": [], "progress": {}}
        if st.exists("_import_checkpoint.json"):
            old = json.loads(st.read_text("_import_checkpoint.json"))
            self.ckpt["done_tables"] = old.get("done_tables", [])
            if "progress" in old:
                self.ckpt["progress"] = old["progress"]
            elif old.get("table"):  # pre-parallel single-cursor format
                self.ckpt["progress"] = {old["table"]: old["stmts_done"]}
        self.batches = 0
        self.conflicts = 0
        self._conflict_lines = []
        st.delete("_import_conflicts.jsonl")  # per-run log

    def write(self):
        self.st.write_text("_import_checkpoint.json",
                           json.dumps(self.ckpt))

    def advance(self, name, done):
        with self.mu:
            self.ckpt["progress"][name] = done
            self.batches += 1
            self.write()
            return self.batches

    def finish_table(self, name):
        with self.mu:
            self.ckpt["done_tables"].append(name)
            self.ckpt["progress"].pop(name, None)
            self.write()

    def record_conflict(self, name, row_sql, err):
        with self.mu:
            self.conflicts += 1
            self._conflict_lines.append(json.dumps(
                {"table": name, "row": row_sql, "error": str(err)}))

    def flush_conflicts(self):
        with self.mu:
            if self._conflict_lines:
                self.st.write_text("_import_conflicts.jsonl",
                                   "\n".join(self._conflict_lines) + "\n")


def _exec_with_dup_handling(session, state, name, stmt, on_duplicate):
    """Run one INSERT batch; on a duplicate-key error under
    on_duplicate='record', retry row-by-row, logging each conflicting row
    (reference: lightning/errormanager — conflicts are data, not crashes)."""
    from .errors import ErrCode
    try:
        session.execute(stmt)
        return
    except TiDBError as e:
        if on_duplicate != "record" or getattr(
                e, "code", None) != ErrCode.DupEntry:
            raise
    from .parser import ast, parse
    parsed = parse(stmt)[0]
    if not isinstance(parsed, ast.InsertStmt):
        raise TiDBError("duplicate in a non-INSERT import statement")
    for row in parsed.values:
        single = ast.InsertStmt(table=parsed.table,
                                columns=list(parsed.columns), values=[row])
        sql = single.restore()
        try:
            session.execute(sql)
        except TiDBError as e2:
            if getattr(e2, "code", None) != ErrCode.DupEntry:
                raise
            state.record_conflict(name, sql, e2)


def _import_one_table(session, st, state, meta, target_db, t, on_duplicate,
                      crash_after_batches):
    name = t["name"]
    session.execute(f"use `{target_db}`")
    with state.mu:
        skip = state.ckpt["progress"].get(name, 0)
    if skip == 0 and not session.infoschema().has_table(target_db, name):
        session.execute(st.read_text(f"{meta['db']}.{name}-schema.sql"))
    if t.get("is_view"):
        state.finish_table(name)
        return
    data_name = f"{meta['db']}.{name}.sql"
    csv_name = f"{meta['db']}.{name}.csv"
    if not st.exists(data_name) and st.exists(csv_name):
        stmts = _csv_to_inserts(st.read_text(csv_name), name)
    else:
        stmts = _split_sql(st.read_text(data_name))
    done = 0
    for stmt in stmts:
        done += 1
        if done <= skip:
            continue
        _exec_with_dup_handling(session, state, name, stmt, on_duplicate)
        batches = state.advance(name, done)
        if (crash_after_batches is not None
                and batches >= crash_after_batches):
            raise TiDBError("import aborted (injected crash)")
    state.finish_table(name)


def import_dump(session, src: str, db_name: str | None = None,
                crash_after_batches: int | None = None, workers: int = 1,
                on_duplicate: str = "error") -> dict:
    """Load a dump produced by dump_database (sql or csv format).

    workers: table-level parallelism — each worker drives its own session
    over the shared domain (reference: lightning's table/index
    concurrency); the checkpoint file is shared and locked.
    on_duplicate: 'error' fails the run on a duplicate key (default);
    'record' logs conflicting rows to _import_conflicts.jsonl and keeps
    going (reference: lightning/errormanager). Known limit: a crash in
    the middle of a row-by-row conflict retry makes the RESUMED run see
    its own previously-inserted rows as conflicts (the checkpoint is
    per-statement); the log may then over-report — it never loses real
    conflicts."""
    if on_duplicate not in ("error", "record"):
        raise TiDBError("on_duplicate must be 'error' or 'record'")
    st = open_storage(src)
    meta = json.loads(st.read_text("metadata.json"))
    target_db = db_name or meta["db"]
    if session.infoschema().schema_by_name(target_db) is None:
        session.execute(f"create database `{target_db}`")
    state = _ImportState(st)
    pending = [t for t in meta["tables"]
               if t["name"] not in state.ckpt["done_tables"]]
    # views depend on base tables: create them LAST, serially
    views = [t for t in pending if t.get("is_view")]
    tables = [t for t in pending if not t.get("is_view")]

    if workers <= 1 or len(tables) <= 1:
        for t in tables + views:
            _import_one_table(session, st, state, meta, target_db, t,
                              on_duplicate, crash_after_batches)
    else:
        from .session import new_session
        errs = []
        emu = threading.Lock()
        it = iter(tables)
        imu = threading.Lock()

        def worker():
            ws = new_session(session.domain)
            while True:
                with imu:
                    t = next(it, None)
                if t is None:
                    return
                try:
                    _import_one_table(ws, st, state, meta, target_db, t,
                                      on_duplicate, crash_after_batches)
                except Exception as e:
                    with emu:
                        errs.append(e)
                    return

        threads = [threading.Thread(target=worker)
                   for _ in range(min(workers, len(tables)))]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        if errs:
            # conflicts recorded before the failure must survive it: the
            # log is the operator's record of what on_duplicate='record'
            # skipped (the checkpoint makes the import resumable, the
            # conflict log is not rebuilt on resume)
            state.flush_conflicts()
            raise errs[0]
        for t in views:
            _import_one_table(session, st, state, meta, target_db, t,
                              on_duplicate, crash_after_batches)
    state.flush_conflicts()
    st.delete("_import_checkpoint.json")
    return {"db": target_db,
            "tables": [t["name"] for t in meta["tables"]],
            "conflicts": state.conflicts}


def _csv_to_inserts(text: str, table: str, batch: int = 256):
    """CSV dump (header row; \\N = NULL) → INSERT statement batches — the
    csv-format twin of the sql loader (reference: lightning/mydump csv
    parser)."""
    import csv
    rdr = csv.reader(io.StringIO(text))
    try:
        next(rdr)  # header
    except StopIteration:
        return

    def lit(v: str) -> str:
        if v == "\\N":
            return "NULL"
        if v.startswith("\\\\"):
            v = v[1:]  # un-escape the doubled leading backslash
        return _str_lit(v)

    rows = []
    for r in rdr:
        rows.append("(" + ", ".join(lit(v) for v in r) + ")")
        if len(rows) >= batch:
            yield f"INSERT INTO `{table}` VALUES " + ",".join(rows)
            rows = []
    if rows:
        yield f"INSERT INTO `{table}` VALUES " + ",".join(rows)


def _split_sql(text: str):
    """Split dump files on ';\n' statement boundaries (values never contain
    that sequence: _sql_lit escapes newlines are impossible in display
    strings, and the writer ends every statement with ';\\n')."""
    for part in text.split(";\n"):
        if part.strip():
            yield part
