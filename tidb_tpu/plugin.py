"""Plugin framework: audit + authentication SPI (reference: plugin/spi.go:32
Manifest, :66 sub-manifests; plugin/audit.go:78 AuditManifest; the audit hook
fires from connection dispatch, server/conn.go:1094).

Plugins here are Python objects registered on the domain (the reference
loads .so manifests; the SPI shape — kind, version, lifecycle callbacks,
event hooks — is the same). Hooks must never break statement execution:
failures are recorded, not raised.
"""

from __future__ import annotations

import threading
import time

KIND_AUDIT = "audit"
KIND_AUTHENTICATION = "authentication"

# audit event classes (reference: plugin/audit.go GeneralEvent classes)
EVENT_CONNECT = "Connect"
EVENT_DISCONNECT = "Disconnect"
EVENT_STMT = "Statement"


class Plugin:
    """SPI base (reference: plugin.Manifest). Subclass and override the
    hooks for the chosen kind."""

    name = "plugin"
    kind = KIND_AUDIT
    version = 1

    def on_init(self, domain):
        pass

    def on_shutdown(self, domain):
        pass

    # -- audit sub-manifest --------------------------------------------------

    def on_general_event(self, session, sql: str, event_class: str):
        pass

    def on_connection_event(self, conn_info: dict, event: str):
        pass

    # -- authentication sub-manifest ----------------------------------------

    def authenticate(self, user: str, host: str, auth_data) -> bool | None:
        """Return True/False to decide, None to fall through to the grant
        tables (reference: AuthenticationManifest.AuthenticateUser)."""
        return None


class PluginRegistry:
    """Domain-level plugin set (reference: plugin.Load + plugin.Audit
    iteration helpers)."""

    _ERRORS_CAP = 64

    def __init__(self, domain):
        self.domain = domain
        self._lock = threading.Lock()
        self._plugins: dict[str, Plugin] = {}
        self.errors: list[str] = []

    def _record_error(self, msg: str):
        with self._lock:
            self.errors.append(msg)
            del self.errors[:-self._ERRORS_CAP]  # bounded

    def load(self, plugin: Plugin):
        # on_init runs OUTSIDE the registry lock: an init that executes SQL
        # re-enters via plugins.list() and would deadlock otherwise
        with self._lock:
            if plugin.name in self._plugins:
                raise ValueError(f"plugin '{plugin.name}' already loaded")
        plugin.on_init(self.domain)
        with self._lock:
            if plugin.name in self._plugins:
                raise ValueError(f"plugin '{plugin.name}' already loaded")
            self._plugins[plugin.name] = plugin

    def unload(self, name: str) -> bool:
        with self._lock:
            p = self._plugins.pop(name, None)
        if p is None:
            return False
        try:
            p.on_shutdown(self.domain)
        except Exception as e:
            self._record_error(f"{name}.on_shutdown: {e}")
        return True

    def list(self):
        with self._lock:
            return list(self._plugins.values())

    def _each(self, kind):
        with self._lock:
            return [p for p in self._plugins.values() if p.kind == kind]

    # -- hook fan-out (failures never break the statement) -------------------

    def audit_general(self, session, sql: str, event_class: str):
        for p in self._each(KIND_AUDIT):
            try:
                p.on_general_event(session, sql, event_class)
            except Exception as e:
                self._record_error(f"{p.name}.on_general_event: {e}")

    def audit_connection(self, conn_info: dict, event: str):
        for p in self._each(KIND_AUDIT):
            try:
                p.on_connection_event(conn_info, event)
            except Exception as e:
                self._record_error(f"{p.name}.on_connection_event: {e}")

    def authenticate(self, user: str, host: str, auth_data) -> bool | None:
        """First definitive answer wins; None = no auth plugin decided."""
        for p in self._each(KIND_AUTHENTICATION):
            try:
                r = p.authenticate(user, host, auth_data)
            except Exception as e:
                self._record_error(f"{p.name}.authenticate: {e}")
                continue
            if r is not None:
                return bool(r)
        return None
