"""Multi-chip MPP bench: the carry-over acceptance record (MULTICHIP_rNN).

Measures, on the 8-device virtual CPU mesh (the same
--xla_force_host_platform_device_count harness the driver's dryrun and
tests/conftest.py use):

  1. Q3-class MPP join+agg WARM ROUNDS: per-round XLA trace/compile
     counts and wall time through the mesh-keyed compiled-fragment
     cache. The zero-recompile acceptance: round 2 and the
     post-within-bucket-INSERT round perform ZERO new traces, with
     bit-exact host parity. (r05 had no MPP-layer cache at all — every
     round re-traced the full SPMD pipeline; the warm trajectory here
     must be strictly below that.)
  2. RADIX-EXCHANGE hot-key convergence: a dominant probe key overflows
     the initial per-sub-bucket capacity and converges via the exact
     next_pow2(need) jump — retries counted, zero dropped rows (parity).
  3. THREADED CHAOS + MESH FENCE: the tests/chaos_harness.py threaded
     catalog (hang/OOM/exchange faults over mixed engines incl.
     tpu-mpp) with an explicit supervisor.fence() injected mid-schedule;
     afterwards residency.verify_ledger() must hold (placement-cache
     bytes accounted, zero drift) and a post-fence MPP query must be
     exact — a fenced mesh never serves stale shards.

Watchdog: a global SIGALRM (BENCH_TIMEOUT_S, default 900) guarantees the
JSON record is written even on a hang — phases already completed keep
their numbers, the record carries ok=false. Emits one JSON line per
phase on stdout (bench.py convention) and writes MULTICHIP_r06.json
(override with MULTICHIP_OUT).
"""

import json
import os
import signal
import sys
import threading
import time

N_DEVICES = 8
OUT_PATH = os.environ.get("MULTICHIP_OUT", "MULTICHIP_r06.json")
TIMEOUT_S = int(os.environ.get("BENCH_TIMEOUT_S", "900"))

# the virtual mesh must exist BEFORE jax initializes a backend
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + f" --xla_force_host_platform_device_count={N_DEVICES}"
    ).strip()

import tidb_tpu  # noqa: F401,E402  (x64 + AOT cache fingerprint)
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from tidb_tpu.testkit import TestKit  # noqa: E402

RECORD = {"n_devices": N_DEVICES, "rc": 0, "ok": False, "skipped": False,
          "phases": {}}


def _emit(obj):
    print(json.dumps(obj), flush=True)


def _last_trace_text(cap=4000) -> str:
    """Most recent finished span trace, rendered (the failed phase's
    post-mortem timeline; see bench.py); "" when tracing never ran."""
    from tidb_tpu.session import tracing
    return tracing.last_trace_text(cap=cap)


def _compile_gauges() -> dict:
    """Compile-service gauges for the record (executor/compile_service):
    pending fragments / persistent-index hits / prewarm counts — a round
    whose first execution was host-served says so."""
    from tidb_tpu.executor import compile_service
    return compile_service.report_gauges()


def _write_record():
    with open(OUT_PATH, "w") as f:
        json.dump(RECORD, f, indent=1)
        f.write("\n")


def _watchdog(signum, frame):
    RECORD["rc"] = 1
    RECORD["error"] = f"global watchdog fired after {TIMEOUT_S}s"
    RECORD["trace"] = _last_trace_text()
    _emit({"metric": "multichip_watchdog", "value": 0, **RECORD})
    _write_record()
    os._exit(1)


def _pipe_stats():
    from tidb_tpu.executor.device_exec import pipe_cache_stats
    return pipe_cache_stats()


def _mk_q3_tk(n_cust=64, n_ord=256, n_line=1000):
    # n_line=1000: 125 rows/shard → bucket 128 with headroom, so the
    # phase-1 within-bucket INSERT stays inside (1024 would sit exactly
    # ON the boundary and the delta would legitimately recompile)
    tk = TestKit()
    tk.must_exec("create database mc")
    tk.must_exec("use mc")
    tk.must_exec("set tidb_mpp_devices = 8")
    if os.environ.get("BENCH_TRACE", "") == "1":
        # opt-in (same comparability rule as bench.py): a failed phase's
        # error line then carries the query's span trace
        tk.must_exec("set tidb_trace_sampling_rate = 1")
    tk.must_exec("""create table customer (
        c_custkey bigint primary key, c_mktsegment varchar(10))""")
    tk.must_exec("""create table orders (
        o_orderkey bigint primary key, o_custkey bigint,
        o_orderdate date, o_shippriority bigint)""")
    tk.must_exec("""create table lineitem (
        l_orderkey bigint, l_extendedprice decimal(15,2),
        l_discount decimal(15,2), l_shipdate date)""")
    segs = ["BUILDING", "MACHINERY", "AUTOMOBILE"]
    tk.must_exec("insert into customer values " + ",".join(
        f"({i}, '{segs[i % 3]}')" for i in range(1, n_cust + 1)))
    tk.must_exec("insert into orders values " + ",".join(
        f"({i}, {(i % n_cust) + 1}, '199{4 + i % 3}-0{1 + i % 9}-15', 0)"
        for i in range(1, n_ord + 1)))
    tk.must_exec("insert into lineitem values " + ",".join(
        f"({(i % n_ord) + 1}, {100 + i}.25, 0.0{i % 8},"
        f" '199{4 + i % 4}-0{1 + i % 9}-02')" for i in range(n_line)))
    return tk


Q3 = """
    select l_orderkey, sum(l_extendedprice * (1 - l_discount)) as rev,
           o_orderdate, o_shippriority
    from customer, orders, lineitem
    where c_mktsegment = 'BUILDING' and c_custkey = o_custkey
      and l_orderkey = o_orderkey and o_orderdate < '1996-03-15'
    group by l_orderkey, o_orderdate, o_shippriority
    order by rev desc, o_orderdate limit 10"""


def _round(tk, q, engine="tpu-mpp"):
    tk.must_exec(f"set tidb_executor_engine = '{engine}'")
    s0 = _pipe_stats()
    t0 = time.perf_counter()
    rows = tk.must_query(q).rows
    wall = time.perf_counter() - t0
    s1 = _pipe_stats()
    return rows, {"wall_s": round(wall, 4),
                  "traces": s1["traces"] - s0["traces"],
                  "compiles": s1["compiles"] - s0["compiles"],
                  # query-path (sync) vs compile-service background split
                  # (executor/compile_service.py): mesh rounds compile
                  # sync today, so bg stays 0 unless prewarm/async ran
                  "sync_compile_s": round(
                      s1["compile_s"] - s0["compile_s"], 4),
                  "compile_s": round(s1["compile_s"] - s0["compile_s"], 4),
                  "bg_compile_s": round(
                      s1["bg_compile_s"] - s0["bg_compile_s"], 4),
                  "pipe_misses": s1["misses"] - s0["misses"],
                  "pipe_hits": s1["hits"] - s0["hits"]}


def phase_warm_rounds():
    from tidb_tpu.executor import mpp_exec
    tk = _mk_q3_tk()
    host, _ = _round(tk, Q3, engine="host")
    frags0 = mpp_exec.MPP_STATS["fragments"]
    r1rows, r1 = _round(tk, Q3)
    r2rows, r2 = _round(tk, Q3)
    assert mpp_exec.MPP_STATS["fragments"] > frags0, "never reached mesh"
    assert r1rows == host and r2rows == host, "mpp/host divergence"
    # within-bucket INSERT: the zero-recompile acceptance round
    tk.must_exec("insert into lineitem values "
                 "(1, 999.25, 0.02, '1994-02-02'),"
                 "(2, 998.25, 0.03, '1995-03-02')")
    host2, _ = _round(tk, Q3, engine="host")
    r3rows, r3 = _round(tk, Q3)
    assert r3rows == host2, "post-INSERT mpp/host divergence"
    ok = (r2["traces"] == 0 and r2["pipe_misses"] == 0
          and r3["traces"] == 0 and r3["pipe_misses"] == 0)
    out = {
        "query": "q3_class_mpp_join_agg",
        "round1_cold": r1, "round2_warm": r2,
        "round3_post_insert_within_bucket": r3,
        "zero_recompile_ok": ok,
        "mpp_gauges": mpp_exec.report_gauges(),
        "compile_gauges": _compile_gauges(),
        # r05 ran the mesh path with EXACT shard shapes and no MPP-layer
        # pipeline cache: every round re-traced the SPMD program (warm
        # trace count == cold trace count). The carry-over's warm
        # trajectory must be strictly below that.
        "r05_trajectory": {"warm_traces_per_round": r1["traces"],
                           "note": "r05: exact shapes, no mesh cache — "
                                   "every round re-traced"},
    }
    assert ok, f"zero-recompile regression failed: {out}"
    assert r2["traces"] < max(r1["traces"], 1), "warm not below r05 line"
    return out


def phase_skew_exchange():
    from tidb_tpu.executor import mpp_exec
    tk = TestKit()
    tk.must_exec("create database skew")
    tk.must_exec("use skew")
    tk.must_exec("set tidb_mpp_devices = 8")
    tk.must_exec("create table dim (k bigint primary key, w bigint)")
    tk.must_exec("insert into dim values " + ",".join(
        f"({i}, {i})" for i in range(1, 65)))
    tk.must_exec("create table fact (a bigint primary key, k bigint, "
                 "v bigint)")
    tk.must_exec("insert into fact values " + ",".join(
        f"({i}, {7 if i <= 224 else (i % 64) + 1}, {i})"
        for i in range(1, 321)))
    tk.must_exec("set tidb_broadcast_join_threshold_count = 30")
    q = ("select count(1), sum(fact.v + dim.w) from fact, dim "
         "where fact.k = dim.k")
    host, _ = _round(tk, q, engine="host")
    ovf0 = mpp_exec.MPP_STATS["exchange_overflow_retries"]
    sh0 = mpp_exec.MPP_STATS["shuffle_joins"]
    rows, r1 = _round(tk, q)
    assert rows == host, "skew round dropped rows (parity failed)"
    retries = mpp_exec.MPP_STATS["exchange_overflow_retries"] - ovf0
    assert mpp_exec.MPP_STATS["shuffle_joins"] > sh0, "no shuffle path"
    assert retries >= 1, "hot key never overflowed the initial capacity"
    rows2, r2 = _round(tk, q)  # learned caps: no rediscovery
    assert rows2 == host and r2["traces"] == 0
    return {"hot_key_rows": 224, "overflow_retries": retries,
            "dropped": 0, "cold": r1, "warm": r2}


def phase_chaos_fence(n_seeds=2):
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "tests"))
    import chaos_harness
    from tidb_tpu.executor import mpp_exec, supervisor
    from tidb_tpu.ops import residency

    fences = []

    def fence_injector(stop):
        # one explicit mesh fence mid-schedule, on top of whatever the
        # catalog's hang injections trigger
        time.sleep(0.5)
        if not stop.is_set():
            supervisor.fence("bench_multichip: injected mesh fence")
            fences.append(1)

    results = []
    for seed in range(n_seeds):
        stop = threading.Event()
        inj = threading.Thread(target=fence_injector, args=(stop,),
                               daemon=True)
        inj.start()
        try:
            stats = chaos_harness.run_threaded_seed(seed, n_threads=4,
                                                    n_ops=6)
        finally:
            stop.set()
            inj.join(timeout=5)
        results.append(stats)
    led = residency.verify_ledger()
    assert led["ok"], f"ledger drift after chaos+fence: {led}"
    # a fenced mesh must serve fresh shards, exactly
    tk = _mk_q3_tk(n_cust=16, n_ord=64, n_line=256)
    host, _ = _round(tk, Q3, engine="host")
    rows, _ = _round(tk, Q3)
    assert rows == host, "post-fence MPP divergence"
    return {"seeds": n_seeds, "fences_injected": sum(fences),
            "ledger": led, "post_fence_parity": True,
            "mpp_place_bytes": mpp_exec.place_cache_bytes(),
            "chaos": [{k: v for k, v in r.items()} for r in results]}


def main():
    signal.signal(signal.SIGALRM, _watchdog)
    signal.alarm(TIMEOUT_S)
    failures = 0
    for name, fn in (("warm_rounds", phase_warm_rounds),
                     ("skew_exchange", phase_skew_exchange),
                     ("chaos_fence", phase_chaos_fence)):
        t0 = time.perf_counter()
        try:
            res = fn()
            res["phase_s"] = round(time.perf_counter() - t0, 2)
            RECORD["phases"][name] = res
            _emit({"metric": f"multichip_{name}", "value": 1, **res})
        except Exception as e:  # noqa: BLE001 — record and continue
            failures += 1
            trace = _last_trace_text()
            RECORD["phases"][name] = {"error": f"{type(e).__name__}: {e}",
                                      "trace": trace}
            _emit({"metric": f"multichip_{name}", "value": 0,
                   "error": str(e), "trace": trace})
    RECORD["ok"] = failures == 0
    RECORD["rc"] = 0 if failures == 0 else 1
    _write_record()
    _emit({"metric": "multichip_record", "value": int(RECORD["ok"]),
           "out": OUT_PATH})
    return RECORD["rc"]


if __name__ == "__main__":
    sys.exit(main())
