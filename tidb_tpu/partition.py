"""Partitioned tables: routing, views, and pruning (reference:
table/tables/partition.go PartitionedTable + locatePartition, and the planner
rule planner/core/rule_partition_processor.go).

Design: each partition is a physical table id; a row's partition is a pure
function of one column's internal value (bare column or YEAR/MONTH/TO_DAYS of
a date column).  The row/index codec, the MVCC store, the columnar cache, and
the delta machinery all operate on physical ids and stay partition-oblivious;
everything partition-aware lives here plus thin dispatch in table.Table.
"""

from __future__ import annotations

from .errors import TiDBError, ErrCode
from .model import PartitionDef, PartitionInfo, TableInfo
from .sqltypes import (
    TYPE_DATE, TYPE_DATETIME, TYPE_NEWDATE, TYPE_TIMESTAMP,
    days_to_date, micros_to_datetime,
)

MAXVALUE = "MAXVALUE"

_PART_FUNCS = ("year", "month", "to_days")

# TO_DAYS('1970-01-01') in MySQL — internal dates count from the unix epoch
_TO_DAYS_EPOCH = 719528


class NoPartitionError(TiDBError):
    def __init__(self, value):
        super().__init__(f"Table has no partition for value {value}",
                         code=ErrCode.NoPartitionForGivenValue)


def build_partition_info(popt, tbl: TableInfo, gen_id) -> PartitionInfo:
    """AST PartitionOpt → PartitionInfo with physical ids allocated via
    gen_id() (reference: ddl/partition.go buildTablePartitionInfo)."""
    from .parser import ast

    expr_node = popt.expr
    func = ""
    if isinstance(expr_node, ast.FuncCall) and expr_node.name in _PART_FUNCS:
        func = expr_node.name
        if len(expr_node.args) != 1 or not isinstance(expr_node.args[0],
                                                      ast.ColumnName):
            raise TiDBError("partition function must take a single column",
                            code=ErrCode.PartitionFunctionIsNotAllowed)
        col_node = expr_node.args[0]
    elif isinstance(expr_node, ast.ColumnName):
        col_node = expr_node
    else:
        raise TiDBError(
            "unsupported partition expression (use a column or "
            "YEAR/MONTH/TO_DAYS of a column)",
            code=ErrCode.PartitionFunctionIsNotAllowed)
    col = tbl.find_column(col_node.name)
    if col is None:
        raise TiDBError(f"Unknown column '{col_node.name}' in partition "
                        "function", code=ErrCode.BadField)

    pinfo = PartitionInfo(type=popt.type, expr=expr_node.restore(),
                          col_name=col.name, func=func, num=popt.num)

    if popt.type == "hash":
        n = popt.num or len(popt.defs)
        if n <= 0:
            raise TiDBError("wrong number of HASH partitions",
                            code=ErrCode.PartitionsMustBeDefined)
        pinfo.num = n
        names = [d[0] for d in popt.defs] if popt.defs else \
            [f"p{i}" for i in range(n)]
        for name in names:
            pinfo.defs.append(PartitionDef(id=gen_id(), name=name))
        return pinfo

    if not popt.defs:
        raise TiDBError("For RANGE/LIST partitions each partition must be "
                        "defined", code=ErrCode.PartitionsMustBeDefined)
    for name, kind, values in popt.defs:
        append_partition_def(pinfo, col, name, kind, values, gen_id)
    return pinfo


def append_partition_def(pinfo: PartitionInfo, col, name, kind, values,
                         gen_id):
    """Validate and append one RANGE/LIST partition definition — shared by
    CREATE TABLE and ALTER TABLE ADD PARTITION (reference: ddl/partition.go
    checkAddPartitionValue)."""
    if pinfo.find_def(name) is not None:
        raise TiDBError(f"Duplicate partition name {name}",
                        code=ErrCode.SameNamePartition)
    if pinfo.type == "range":
        if kind != "less_than" or len(values) != 1:
            raise TiDBError("RANGE partitions require VALUES LESS THAN",
                            code=ErrCode.PartitionRequiresValues)
        prev = pinfo.defs[-1].less_than if pinfo.defs else None
        bound = _cast_bound(values[0], col, pinfo.func)
        if prev == MAXVALUE or (prev is not None and bound != MAXVALUE
                                and bound <= prev):
            raise TiDBError(
                "VALUES LESS THAN value must be strictly increasing for "
                "each partition", code=ErrCode.RangeNotIncreasing)
        pinfo.defs.append(PartitionDef(id=gen_id(), name=name,
                                       less_than=bound))
    else:  # list
        if kind != "in":
            raise TiDBError("LIST partitions require VALUES IN",
                            code=ErrCode.PartitionRequiresValues)
        vals = [_cast_bound(v, col, pinfo.func) if v is not None else None
                for v in values]
        pinfo.defs.append(PartitionDef(id=gen_id(), name=name,
                                       in_values=vals))


def _cast_bound(node_or_value, col, func):
    """Evaluate/cast a partition bound literal into the comparison domain:
    the column's internal representation for bare-column partitioning, a
    plain int for YEAR/MONTH/TO_DAYS."""
    from .parser import ast
    v = node_or_value
    if isinstance(v, str) and v == MAXVALUE:
        return MAXVALUE
    if isinstance(v, ast.ExprNode):
        from .expression import ExprBuilder, Schema
        v = ExprBuilder(Schema([])).build(v).eval_scalar()
    if func:
        return int(v)
    from .table import cast_value
    return cast_value(v, col.ftype)


def check_partition_keys(tbl: TableInfo):
    """MySQL rule: every unique key (incl. the PK) on a partitioned table
    must include the partitioning column (reference: ddl/partition.go
    checkPartitionKeysConstraint)."""
    p = tbl.partition
    if p is None:
        return
    pcol = p.col_name.lower()
    if tbl.pk_is_handle:
        pk = next((c for c in tbl.columns if c.id == tbl.pk_col_id), None)
        if pk is not None and pk.name.lower() != pcol:
            raise TiDBError(
                "A PRIMARY KEY must include all columns in the table's "
                "partitioning function", code=ErrCode.UniqueKeyNeedAllFieldsInPf)
    for idx in tbl.indexes:
        if not idx.unique:
            continue
        if pcol not in {ic.name.lower() for ic in idx.columns}:
            raise TiDBError(
                f"A {'PRIMARY KEY' if idx.primary else 'UNIQUE INDEX'} must "
                "include all columns in the table's partitioning function",
                code=ErrCode.UniqueKeyNeedAllFieldsInPf)


# -- row routing -------------------------------------------------------------

def make_part_fn(info: TableInfo):
    """-> fn(row_dict) -> partition value (int/bytes/None).  Row dicts hold
    internal representations ({col_id: value})."""
    p = info.partition
    col = info.find_column(p.col_name)
    cid = col.id
    func = p.func
    if not func:
        return lambda row: row.get(cid)
    is_dt = col.ftype.tp in (TYPE_DATETIME, TYPE_TIMESTAMP)

    def _to_date(v):
        if is_dt:
            return micros_to_datetime(int(v)).date()
        return days_to_date(int(v))

    if func == "year":
        return lambda row: (None if row.get(cid) is None
                            else _to_date(row[cid]).year)
    if func == "month":
        return lambda row: (None if row.get(cid) is None
                            else _to_date(row[cid]).month)
    # to_days
    if is_dt:
        return lambda row: (None if row.get(cid) is None
                            else int(row[cid]) // 86_400_000_000
                            + _TO_DAYS_EPOCH)
    return lambda row: (None if row.get(cid) is None
                        else int(row[cid]) + _TO_DAYS_EPOCH)


def locate_partition(pinfo: PartitionInfo, pval) -> PartitionDef:
    """Partition value → PartitionDef (reference: partition.go
    locatePartition). NULL routes to the first range partition (MySQL
    semantics), hashes as 0, and must be listed for LIST."""
    if pinfo.type == "hash":
        h = 0 if pval is None else _hash_val(pval)
        return pinfo.defs[h % pinfo.num]
    if pinfo.type == "range":
        if pval is None:
            return pinfo.defs[0]
        for d in pinfo.defs:
            if d.less_than == MAXVALUE or _lt(pval, d.less_than):
                return d
        raise NoPartitionError(_fmt(pval))
    # list
    for d in pinfo.defs:
        for v in d.in_values:
            if (v is None and pval is None) or (v is not None and v == pval):
                return d
    raise NoPartitionError(_fmt(pval))


def _hash_val(v):
    if isinstance(v, (bytes, bytearray)):
        # stable across processes (python str hash is seeded)
        import zlib
        return zlib.crc32(bytes(v))
    return abs(int(v))


def _lt(a, b):
    if isinstance(a, (bytes, bytearray)) != isinstance(b, (bytes, bytearray)):
        return False
    return a < b


def _fmt(v):
    return v.decode("utf-8", "replace") if isinstance(v, bytes) else v


# -- physical partition views -------------------------------------------------

def partition_view(info: TableInfo, pdef: PartitionDef) -> TableInfo:
    """A TableInfo clone whose id is the partition's physical id; the codec
    and store layers see a plain table.  Cached per (info, partition)."""
    cache = getattr(info, "_pviews", None)
    if cache is None:
        cache = {}
        object.__setattr__(info, "_pviews", cache)
    view = cache.get(pdef.id)
    if view is None:
        view = TableInfo.from_json(info.to_json())
        view.id = pdef.id
        view.partition = None
        cache[pdef.id] = view
    return view


def index_phys_ids(info: TableInfo) -> list:
    """Physical ids whose key ranges carry this table's index entries: the
    table itself, plus every partition for a partitioned table."""
    ids = [info.id]
    if info.partition is not None:
        ids += [d.id for d in info.partition.defs]
    return ids


# -- planner pruning ----------------------------------------------------------

def prune_partitions(info: TableInfo, defs, conds):
    """Filter candidate PartitionDefs with scan predicates (reference:
    rule_partition_processor.go). Handles cmp(col, const) on the partition
    column: equality prunes every type; ranges prune RANGE tables."""
    p = info.partition
    if not conds:
        return defs
    from .statistics.selectivity import _col_const
    pcol = p.col_name.lower()
    fn = make_part_fn(info)
    col_id = info.find_column(p.col_name).id
    from .table import cast_value
    col = info.find_column(p.col_name)
    out = list(defs)
    for cond in conds:
        cc = _col_const(cond)
        if cc is None:
            continue
        ecol, v, op = cc
        if ecol.name.lower() != pcol:
            continue
        try:
            iv = cast_value(v, col.ftype)
        except Exception:
            continue
        pv = fn({col_id: iv})
        if op == "eq":
            try:
                target = locate_partition(p, pv)
            except NoPartitionError:
                return []
            out = [d for d in out if d.id == target.id]
        elif p.type == "range" and not p.func and op in ("lt", "le", "gt", "ge"):
            out = [d for d in out if _range_may_match(p, d, pv, op)]
    return out


def _range_may_match(pinfo, pdef, v, op):
    """Could any row in range-partition pdef satisfy `col OP v`?"""
    idx = next(i for i, d in enumerate(pinfo.defs) if d.id == pdef.id)
    lo = None if idx == 0 else pinfo.defs[idx - 1].less_than  # inclusive-from
    hi = pdef.less_than                                       # exclusive
    if lo == MAXVALUE:
        return False  # unreachable layout, defensive
    if op in ("lt", "le"):
        # need a row with col < v (or <=): partition start must be below v
        if lo is None:
            return True
        return _lt(lo, v) or (op == "le" and lo == v)
    # gt / ge: need a row with col > v (or >=): partition end must be above v
    if hi == MAXVALUE:
        return True
    return _lt(v, hi)
