"""Columnar storage layer on top of the row KV store — the TiFlash-replica
role (reference: MPP reads columnar replicas; here a per-table columnar cache
materialized from the MVCC row store and invalidated by write watermarks)."""

from .columnar import ColumnarCache

__all__ = ["ColumnarCache"]
