"""Server process entry: flags → config layering, HTTP status API,
graceful startup/shutdown (reference: tidb-server/main.go,
server/http_status.go)."""

import json
import sys
import threading
import urllib.request

import pytest

sys.path.insert(0, "tests")

from tidb_tpu.config import Config, load_config
from tidb_tpu.server.main import build_arg_parser, resolve_config


def test_config_defaults():
    cfg = Config()
    assert cfg.port == 4000 and cfg.status.status_port == 10080


def test_config_toml_and_flag_override(tmp_path):
    p = tmp_path / "cfg.toml"
    p.write_text("""
host = "0.0.0.0"
port = 4567
[performance]
mem-quota-query = 123456
executor-engine = "host"
[status]
status-port = 9999
""")
    args = build_arg_parser().parse_args(
        ["--config", str(p), "--port", "5000"])
    cfg = resolve_config(args)
    assert cfg.host == "0.0.0.0"
    assert cfg.port == 5000  # CLI wins over file
    assert cfg.performance.mem_quota_query == 123456
    assert cfg.performance.executor_engine == "host"
    assert cfg.status.status_port == 9999


def test_config_strict_rejects_unknown(tmp_path):
    p = tmp_path / "bad.toml"
    p.write_text("nonsense = 1\n")
    with pytest.raises(ValueError):
        load_config(str(p), strict=True)
    # non-strict only warns
    load_config(str(p), strict=False)


@pytest.fixture()
def running_server():
    """The pieces run_server composes, on ephemeral ports (run_server
    itself installs signal handlers, which only work on the main thread)."""
    from tidb_tpu.kv import new_store
    from tidb_tpu.session import bootstrap_domain
    from tidb_tpu.server.server import MySQLServer
    from tidb_tpu.server.http_status import StatusServer
    domain = bootstrap_domain(new_store())
    sql = MySQLServer(domain, port=0).start()
    status = StatusServer(domain, sql, port=0).start()
    yield domain, sql, status
    status.shutdown()
    sql.shutdown()


def _get(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=5) as r:
        return r.status, r.read().decode()


def test_status_api(running_server):
    domain, sql, status = running_server
    code, body = _get(status.port, "/status")
    assert code == 200
    st = json.loads(body)
    assert st["version"].endswith("tpu-htap") and "kv_engine" in st

    from tidb_tpu.session import new_session
    s = new_session(domain)
    s.execute("create table st (a int primary key)")
    s.execute("insert into st values (1)")
    s.execute("create index i_a on st (a)")

    code, body = _get(status.port, "/schema")
    assert "test" in json.loads(body)
    code, body = _get(status.port, "/schema/test")
    assert "st" in json.loads(body)
    code, body = _get(status.port, "/schema/test/st")
    tbl = json.loads(body)
    assert tbl["name"] == "st"

    code, body = _get(status.port, "/ddl/history")
    hist = json.loads(body)
    assert any(j["type"] == "add_index" and j["state"] == "synced"
               for j in hist)

    code, body = _get(status.port, "/metrics")
    assert "executor_statement_total" in body
    assert "server_connections" in body

    code, body = _get(status.port, "/regions")
    assert json.loads(body)

    # 404 for unknown path
    import urllib.error
    with pytest.raises(urllib.error.HTTPError):
        _get(status.port, "/nope")


def test_wire_and_status_together(running_server):
    domain, sql, status = running_server
    from test_server import MiniClient
    c = MiniClient(sql.port)
    c.query("create table wt (a int primary key)")
    c.query("insert into wt values (7)")
    kind, payload = c.query("select a from wt")
    assert payload[1] == [("7",)]
    code, body = _get(status.port, "/schema/test")
    assert "wt" in json.loads(body)


def test_version_flag(capsys):
    from tidb_tpu.server.main import main
    assert main(["--version"]) == 0
    assert "tpu-htap" in capsys.readouterr().out


def test_config_check_mode(tmp_path, capsys):
    from tidb_tpu.server.main import main
    p = tmp_path / "ok.toml"
    p.write_text("port = 4001\n")
    assert main(["--config", str(p), "--config-check"]) == 0
    bad = tmp_path / "bad.toml"
    bad.write_text("bogus = true\n")
    assert main(["--config", str(bad), "--config-check"]) == 1
