"""tpu-htap: a TPU-native distributed SQL engine with TiDB's capability surface.

Architecture (see SURVEY.md §7): the control plane — MySQL-dialect parser,
cost-based planner, MVCC transactions, online DDL, catalog — runs host-side in
Python (C++ for the hot codecs/storage in later rounds); the data plane
executes columnar batches as JAX/XLA kernels, with ``shard_map`` collectives
over ICI/DCN taking the role of the reference's MPP exchanges
(reference: planner/core/fragment.go, store/copr/mpp.go) and coprocessor
fan-out (reference: store/copr/coprocessor.go).

Import side effect: enables jax x64 so decimal aggregation (scaled int64) is
exact on device — the north star requires bit-exact parity (BASELINE.md).
"""

import os as _os

# XLA:CPU's AOT loader logs a ~3KB ERROR line per cached program because the
# compile-time machine string carries XLA-internal tuning pseudo-features
# (+prefer-no-scatter/+prefer-no-gather) the loader doesn't recognize; the
# real ISA features match (same machine). Silence the C++ log stream unless
# the operator asked for it. Must be set before the first jax backend init.
_os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")

import jax as _jax

_jax.config.update("jax_enable_x64", True)

# Persistent compilation cache: fused fragment programs (a TPC-H query is ONE
# XLA program; Q18 costs ~30s to build) are compiled once per MACHINE, not
# once per process — the reference's prepared-plan amortization idea
# (planner/core/cache.go) applied at the XLA layer. Opt out with
# TIDB_TPU_JAX_CACHE=off; override the location with TIDB_TPU_JAX_CACHE=<dir>.


def _host_fingerprint() -> str:
    """Host-machine-feature fingerprint scoping the AOT compile cache.

    The XLA:CPU cache key ignores host CPU features: an AOT entry
    compiled on a different machine (or by a different jax) loads with a
    ~3KB "could lead to SIGILL" warning PER PROGRAM and mis-tuned code
    (observed cross-machine in MULTICHIP_r05: mismatched feature sets on
    every load). Keying the cache directory by (cpu flags, machine arch,
    jax version) makes a mismatched artifact UNREACHABLE — stale entries
    are skipped silently because another host simply writes to a
    different subdirectory. NOTE: same-host entries can still print the
    loader's mismatch warning — XLA bakes option pseudo-features
    (+prefer-no-scatter/+prefer-no-gather) into the compile target and
    the loader's naive comparison flags them against the real host flag
    set; those entries ARE this machine's and are safe (and the warning
    stream is silenced via TF_CPP_MIN_LOG_LEVEL above). The fingerprint
    guards the cross-machine case only."""
    import hashlib as _hl
    import platform as _pl
    try:
        with open("/proc/cpuinfo") as _f:
            _flags = next((ln for ln in _f if ln.startswith("flags")), "")
    except OSError:
        _flags = ""
    return _hl.sha1(
        (_flags + _pl.machine() + _jax.__version__).encode()
    ).hexdigest()[:12]


_cache_dir = _os.environ.get("TIDB_TPU_JAX_CACHE", "")
if _cache_dir != "off":
    # EVERY cache location — the default AND an explicit
    # TIDB_TPU_JAX_CACHE=<dir> (typically a network share) — is scoped by
    # the host fingerprint subdirectory: a shared dir populated by a
    # machine with a different feature set can never serve its artifacts
    # here (they'd load "could lead to SIGILL"-style), they are skipped
    # silently by construction.
    if not _cache_dir:
        _cache_dir = _os.path.join(
            _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))),
            ".jaxcache")
    try:
        _cache_dir = _os.path.join(_cache_dir, _host_fingerprint())
        _os.makedirs(_cache_dir, exist_ok=True)
        _jax.config.update("jax_compilation_cache_dir", _cache_dir)
        # cache every fragment: the default 1s/small-entry filters would
        # skip the many sub-second shrink-to-fit recompiles
        _jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        _jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:
        pass  # cache is an optimization; never block startup on it

__version__ = "0.1.0"

