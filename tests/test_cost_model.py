"""Unified cost search (reference: planner/core/find_best_task.go DP +
the tidb_opt_*_factor sysvars): one calibrated currency prices access
paths, join variants and engine placement; plans flip by SETting the
constants — never by editing code."""

import numpy as np
import pytest

from tidb_tpu.planner.cost_model import (
    COST_VARS, CostModel, apply_calibration, calibrate)
from tidb_tpu.testkit import TestKit


@pytest.fixture()
def tk():
    tk = TestKit()
    tk.must_exec("use test")
    return tk


def _plan(tk, sql):
    return "\n".join(r[0] + "|" + r[1] for r in
                     tk.must_query("explain " + sql).rows)


def _vplan(tk, sql):
    return [(r[0], r[1], r[2]) for r in
            tk.must_query("explain format='verbose' " + sql).rows]


class TestCalibration:
    def test_calibrate_returns_all_host_constants(self):
        vals = calibrate(n=1 << 14)
        for name, _d in COST_VARS:
            assert name in vals or name == "tidb_opt_scan_row_cost" or \
                vals.get(name) is not None, name
        assert vals["tidb_opt_scan_row_cost"] == 1.0
        # seeks are pointer-chasing; scans are vectorized — any sane
        # machine measures seeks at least several scan-rows each
        assert vals["tidb_opt_seek_cost"] > 1.0
        assert vals["tidb_opt_hash_build_cost"] > 0

    def test_apply_calibration_installs_globals(self, tk):
        vals = apply_calibration(tk.domain, {"tidb_opt_seek_cost": 123.5})
        assert vals["tidb_opt_seek_cost"] == 123.5
        assert tk.must_query(
            "select @@global.tidb_opt_seek_cost").rows == [("123.5",)]
        # sessions planning after this read the measured constant
        cm = CostModel.from_ctx(tk.session)
        assert cm.seek == 123.5

    def test_breakeven_derives_from_constants(self):
        cm = CostModel(1.0, 8.0, 30.0, 2.0, 0.05, 2.0, 0.02, 195000.0)
        assert 60000 < cm.device_breakeven_rows() < 70000


class TestPlanFlips:
    def _setup_join(self, tk):
        tk.must_exec("create table big (k bigint, v bigint)")
        tk.must_exec("create table small (k bigint primary key, w bigint)")
        rng = np.random.default_rng(8)
        tk.must_exec("insert into big values " + ",".join(
            f"({int(rng.integers(1, 200))}, {i})" for i in range(2000)))
        tk.must_exec("insert into small values " + ",".join(
            f"({i}, {i * 3})" for i in range(1, 5001)))
        tk.must_exec("analyze table big")
        tk.must_exec("analyze table small")

    def test_seek_cost_flips_index_join(self, tk):
        """Same query, same stats: the join variant flips purely on the
        calibrated seek constant."""
        self._setup_join(tk)
        q = ("select count(*) from big, small where big.k = small.k "
             "and big.v < 100")
        tk.must_exec("set tidb_opt_seek_cost = 0.001")
        tk.must_exec("set tidb_opt_seek_base = 0.001")
        assert "IndexJoin" in _plan(tk, q)
        tk.must_exec("set tidb_opt_seek_cost = 100000")
        tk.must_exec("set tidb_opt_seek_base = 100000")
        assert "IndexJoin" not in _plan(tk, q)

    def test_seek_cost_flips_access_path(self, tk):
        tk.must_exec("create table ap (a bigint, b bigint, index ia (a))")
        rng = np.random.default_rng(9)
        tk.must_exec("insert into ap values " + ",".join(
            f"({int(rng.integers(0, 500))}, {i})" for i in range(3000)))
        tk.must_exec("analyze table ap")
        q = "select sum(b) from ap where a = 7"
        tk.must_exec("set tidb_opt_seek_cost = 0.001")
        tk.must_exec("set tidb_opt_seek_base = 0.001")
        assert "IndexLookUp" in _plan(tk, q)
        tk.must_exec("set tidb_opt_seek_cost = 1000000")
        tk.must_exec("set tidb_opt_seek_base = 1000000")
        assert "IndexLookUp" not in _plan(tk, q)

    def test_engine_placement_flips_on_dispatch_cost(self, tk):
        """The agg's host-vs-device placement comes from the same
        currency: a huge dispatch constant pins host, a tiny one pins
        the device pipeline (auto engine mode consults the choice)."""
        tk.must_exec("create table ep (g bigint, v bigint)")
        tk.must_exec("insert into ep values " + ",".join(
            f"({i % 7}, {i})" for i in range(4000)))
        tk.must_exec("analyze table ep")
        q = "select g, sum(v) from ep group by g"
        tk.must_exec("set tidb_opt_device_dispatch_cost = 1")
        v = _vplan(tk, q)
        agg = next(r for r in v if "HashAgg" in r[0])
        assert "tpu-agg" in agg[1] and "host-agg" in agg[1]
        import tidb_tpu.planner.physical  # noqa: F401
        plan = tk.session.plan_query(
            __import__("tidb_tpu.parser", fromlist=["parse"]).parse(q)[0])
        from tidb_tpu.planner.logical import Aggregation
        node = plan
        while not isinstance(node, Aggregation):
            node = node.child
        assert node.engine_choice == "tpu"
        tk.must_exec("set tidb_opt_device_dispatch_cost = 1e12")
        plan = tk.session.plan_query(
            __import__("tidb_tpu.parser", fromlist=["parse"]).parse(q)[0])
        node = plan
        while not isinstance(node, Aggregation):
            node = node.child
        assert node.engine_choice == "host"


class TestVerboseCosts:
    def test_every_node_priced_in_one_currency(self, tk):
        tk.must_exec("create table vc1 (k bigint, v bigint)")
        tk.must_exec("create table vc2 (k bigint, w bigint)")
        tk.must_exec("insert into vc1 values (1,1),(2,2),(3,3)")
        tk.must_exec("insert into vc2 values (1,9),(2,8)")
        rows = _vplan(tk, (
            "select vc1.k, sum(w) from vc1, vc2 where vc1.k = vc2.k "
            "group by vc1.k order by vc1.k"))
        # every operator row carries a numeric estCost
        for rid, cost, _info in rows:
            assert cost != "-", f"{rid} has no cost"
            float(cost.split()[0])
        # costs accumulate downward: the root is at least its child
        costs = [float(c.split()[0]) for _r, c, _i in rows]
        assert costs[0] >= costs[-1]
