"""Expression layer (reference: expression/ — tree, vectorized eval,
builtin registry, aggregation descriptors)."""

from .core import (
    Column, Constant, Expression, ScalarFunc, const_null, phys_kind,
    K_DEC, K_FLOAT, K_INT, K_STR, K_DATE, like_to_regex,
)
from .builder import (
    ColumnRef, ExprBuilder, Schema, build_in_set, infer_arith_type,
    literal_to_constant, unify_types,
)
from .aggregation import AggFuncDesc, infer_agg_type

__all__ = [
    "Column", "Constant", "Expression", "ScalarFunc", "const_null",
    "phys_kind", "K_DEC", "K_FLOAT", "K_INT", "K_STR", "K_DATE",
    "like_to_regex", "ColumnRef", "ExprBuilder", "Schema", "build_in_set",
    "infer_arith_type", "literal_to_constant", "unify_types",
    "AggFuncDesc", "infer_agg_type",
]
