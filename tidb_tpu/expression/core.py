"""Expression tree with vectorized evaluation
(reference: expression/expression.go — Column/Constant/ScalarFunction with
VecEval*; the 281 hand+generated vec builtins collapse here into numpy
ufunc compositions, which is also exactly the trace a jax kernel records).

Evaluation contract: ``expr.eval(chunk) -> (data, nulls)`` where data is a
numpy array in the column's physical representation (see utils/chunk.py) and
nulls is a bool mask. Decimals are scaled int64 at ``expr.ftype.scale``.
"""

from __future__ import annotations

import re

import numpy as np

from ..errors import TiDBError
from ..sqltypes import (
    DEFAULT_DIV_PRECISION_INCREMENT, FLOAT_TYPES, INT_TYPES, POW10,
    STRING_TYPES, TYPE_DATE, TYPE_DATETIME, TYPE_DOUBLE, TYPE_DURATION,
    TYPE_JSON, TYPE_LONG, TYPE_LONGLONG, TYPE_NEWDATE, TYPE_NEWDECIMAL,
    TYPE_NULL, TYPE_TIMESTAMP, TYPE_VARCHAR, TYPE_YEAR, FieldType,
    UNSPECIFIED_LENGTH, days_to_date, micros_to_datetime,
)
from ..utils.chunk import Chunk, np_dtype_for

# physical kinds
K_INT = "i"      # int64 (ints, year, duration-us, datetime-us)
K_DEC = "d"      # scaled int64
K_FLOAT = "f"    # float64/float32
K_STR = "s"      # object array of bytes
K_DATE = "t"     # int32 days


def phys_kind(ft: FieldType) -> str:
    tp = ft.tp
    if tp == TYPE_NEWDECIMAL:
        return K_DEC
    if tp in FLOAT_TYPES:
        return K_FLOAT
    if tp in STRING_TYPES or tp == TYPE_JSON:
        return K_STR
    if tp in (TYPE_DATE, TYPE_NEWDATE):
        return K_DATE
    return K_INT


class Expression:
    ftype: FieldType = None

    def eval(self, chunk: Chunk):
        raise NotImplementedError

    def eval_scalar_internal(self, row=None):
        """Evaluate as a constant (no column refs) -> value in the
        INTERNAL physical representation (decimals are scaled ints at
        ftype.scale, dates are day counts). For consumers that pair the
        value with the ftype (DML conversion, constant folding)."""
        data, nulls = self.eval(_EMPTY_ONE)
        if nulls[0]:
            return None
        v = data[0]
        return v.item() if isinstance(v, np.generic) else v

    def eval_scalar(self, row=None):
        """Evaluate as a constant (no column refs) -> user-facing python
        value. Decimals carry their scale as decimal.Decimal — the
        internal scaled int (0.3 stored as 3 at scale 1) must never leak
        to consumers that drop the ftype (user variables, SET, defaults);
        that leak was the historical `SET @r = 0.3` → '3' bug."""
        v = self.eval_scalar_internal(row)
        if (v is not None and self.ftype is not None
                and phys_kind(self.ftype) == K_DEC):
            import decimal
            return decimal.Decimal(int(v)).scaleb(-(self.ftype.scale or 0))
        return v

    def columns_used(self, acc: set):
        pass

    def transform_columns(self, fn):
        """Return a copy with every Column node replaced by fn(col)."""
        return self

    def __repr__(self):
        return f"<{type(self).__name__}>"


class Column(Expression):
    """Reference to the idx-th column of the input chunk."""

    def __init__(self, idx: int, ftype: FieldType, name: str = ""):
        self.idx = idx
        self.ftype = ftype
        self.name = name

    def eval(self, chunk: Chunk):
        col = chunk.columns[self.idx]
        return col.data, col.nulls

    def columns_used(self, acc: set):
        acc.add(self.idx)

    def transform_columns(self, fn):
        return fn(self)

    def __repr__(self):
        return f"Col#{self.idx}({self.name})"


class Constant(Expression):
    # prepared-statement parameter provenance (planner/plan_cache.py): set
    # when this constant came from a '?' marker, so a cached plan can rebind
    # it in place; param_conv records the compare-refinement applied to the
    # raw value ("date"/"datetime"/"float") so rebinding can redo it.
    param_idx = None
    param_conv = None

    def __init__(self, value, ftype: FieldType):
        self.value = value
        self.ftype = ftype

    def eval(self, chunk: Chunk):
        n = chunk.num_rows if chunk.num_cols else 1
        dt = np_dtype_for(self.ftype)
        if self.value is None:
            return _null_fill_array(self.ftype, n), np.ones(n, dtype=bool)
        if dt is object:
            data = np.full(n, self.value, dtype=object)
        else:
            data = np.full(n, self.value, dtype=dt)
        return data, np.zeros(n, dtype=bool)

    def __repr__(self):
        return f"Const({self.value})"


_EMPTY_ONE = Chunk([])


def _null_fill_array(ft, n):
    """All-null output buffer with a type-safe fill (see
    chunk.null_fill_value)."""
    dt = np_dtype_for(ft)
    if dt is object:
        from ..utils.chunk import null_fill_value
        return np.full(n, null_fill_value(ft), dtype=object)
    return np.zeros(n, dtype=dt)


def const_null() -> Constant:
    return Constant(None, FieldType(tp=TYPE_NULL))


class OuterRef(Expression):
    """Marker for a correlated reference to an ENCLOSING query's column,
    produced only during a decorrelation-analysis pass (OuterScope with
    mark=True). The planner's decorrelation rule (reference:
    planner/core/optimizer.go:73-91 decorrelate + expression_rewriter.go)
    rewrites eq(OuterRef, inner_expr) predicates into semi/anti join keys;
    any OuterRef that survives planning means the rewrite bailed and the
    Apply fallback runs instead — evaluating one is always a bug."""

    __slots__ = ("idx", "ftype", "name")

    def __init__(self, idx, ftype, name=""):
        self.idx = idx        # column position in the OUTER schema
        self.ftype = ftype
        self.name = name

    def eval(self, chunk):
        raise TiDBError("internal: OuterRef survived decorrelation")

    def columns_used(self, acc: set):
        pass  # refers to the outer schema, not this one

    def transform_columns(self, fn):
        return self

    def __repr__(self):
        return f"outer({self.name or self.idx})"


class SubqueryApply(Expression):
    """Correlated subquery evaluated per distinct outer binding — the
    reference's Apply operator (planner/core/logical_plans.go LogicalApply,
    executor/parallel_apply.go), realized as an expression: outer rows are
    grouped by the values of the referenced outer columns, the subquery
    re-runs once per distinct binding (memoized), results scatter back.

    modes: 'scalar' (single-value subquery), 'exists'/'not_exists',
    'in'/'not_in' (target expr membership), ('any'|'all', op) quantified
    comparisons. `sub_ft` is the subquery's output column type — membership
    and quantified compares coerce both sides to a unified type, matching
    the uncorrelated build_in_set path."""

    def __init__(self, runner, outer_cols, mode, ftype, target=None,
                 sub_ft=None):
        self.runner = runner          # fn(binding_tuple) -> list of row tuples
        self.outer_cols = outer_cols  # [Column] over the LOCAL schema
        self.mode = mode
        self.ftype = ftype
        self.target = target          # membership/compare target expr
        self.sub_ft = sub_ft
        self._cache = {}

    def columns_used(self, acc: set):
        for c in self.outer_cols:
            c.columns_used(acc)
        if self.target is not None:
            self.target.columns_used(acc)

    def transform_columns(self, fn):
        e = SubqueryApply(self.runner,
                          [c.transform_columns(fn) for c in self.outer_cols],
                          self.mode, self.ftype,
                          None if self.target is None
                          else self.target.transform_columns(fn),
                          sub_ft=self.sub_ft)
        e._cache = self._cache
        return e

    def _coerce_pair(self):
        """(convert_target, convert_sub) closures unifying both sides."""
        from ..table import convert_internal
        from .builder import unify_types  # late: avoid import cycle
        common = unify_types([self.target.ftype, self.sub_ft or
                              self.target.ftype])
        tft = self.target.ftype
        sft = self.sub_ft or tft

        def conv_t(v):
            return None if v is None else convert_internal(v, tft, common)

        def conv_s(v):
            return None if v is None else convert_internal(v, sft, common)

        return conv_t, conv_s

    def __repr__(self):
        return f"apply:{self.mode}({', '.join(map(repr, self.outer_cols))})"

    def _rows_for(self, key):
        rows = self._cache.get(key, _MISSING)
        if rows is _MISSING:
            rows = self.runner(key)
            self._cache[key] = rows
        return rows

    def eval(self, chunk: Chunk):
        n = chunk.num_rows
        pairs = [c.eval(chunk) for c in self.outer_cols]
        data = _null_fill_array(self.ftype, n)
        nulls = np.zeros(n, dtype=bool)
        quant = isinstance(self.mode, tuple)
        if self.mode in ("in", "not_in") or quant:
            tdata, tnulls = self.target.eval(chunk)
            conv_t, conv_s = self._coerce_pair()
        neg = self.mode in ("not_exists", "not_in")
        for i in range(n):
            key = tuple(None if nu[i] else _as_py(d[i]) for d, nu in pairs)
            rows = self._rows_for(key)
            if quant:
                data[i], nulls[i] = self._eval_quant(
                    rows, None if tnulls[i] else conv_t(_as_py(tdata[i])),
                    conv_s)
            elif self.mode in ("exists", "not_exists"):
                data[i] = int(bool(rows)) ^ int(neg)
            elif self.mode == "scalar":
                if len(rows) > 1:
                    raise TiDBError("Subquery returns more than 1 row")
                v = rows[0][0] if rows else None
                if v is None:
                    nulls[i] = True
                else:
                    data[i] = v
            else:  # in / not_in: MySQL three-valued membership
                vals = {conv_s(r[0]) for r in rows if r[0] is not None}
                has_null = any(r[0] is None for r in rows)
                if tnulls[i]:
                    # NULL IN (non-empty) → NULL; NULL IN (empty) → false
                    if rows:
                        nulls[i] = True
                    else:
                        data[i] = int(neg)
                    continue
                tv = conv_t(_as_py(tdata[i]))
                if tv in vals:
                    data[i] = int(not neg)
                elif has_null:
                    nulls[i] = True
                else:
                    data[i] = int(neg)
        return data, nulls

    def _eval_quant(self, rows, tv, conv_s):
        """Three-valued ANY/ALL comparison. tv None means NULL target.
        Returns (value, is_null)."""
        import operator as _op
        kind, op = self.mode
        cmp = {"eq": _op.eq, "ne": _op.ne, "lt": _op.lt, "le": _op.le,
               "gt": _op.gt, "ge": _op.ge}[op]
        if not rows:
            return (0, False) if kind == "any" else (1, False)
        if tv is None:
            return 0, True
        vals = [conv_s(r[0]) for r in rows]
        has_null = any(v is None for v in vals)
        hits = [cmp(tv, v) for v in vals if v is not None]
        if kind == "any":
            if any(hits):
                return 1, False
            return (0, True) if has_null else (0, False)
        # all: false beats null beats true
        if not all(hits):
            return 0, False
        return (0, True) if has_null else (1, False)


_MISSING = object()


def _as_py(v):
    return v.item() if isinstance(v, np.generic) else v


class ScalarFunc(Expression):
    def __init__(self, op: str, args: list, ftype: FieldType, extra=None):
        self.op = op
        self.args = args
        self.ftype = ftype
        self.extra = extra  # op-specific payload (e.g. IN value set, LIKE regex)

    def eval(self, chunk: Chunk):
        fn = _DISPATCH.get(self.op)
        if fn is None:
            raise TiDBError(f"unsupported scalar function {self.op}")
        return fn(self, chunk)

    def columns_used(self, acc: set):
        for a in self.args:
            a.columns_used(acc)

    def transform_columns(self, fn):
        return ScalarFunc(self.op, [a.transform_columns(fn) for a in self.args],
                          self.ftype, self.extra)

    def __repr__(self):
        return f"{self.op}({', '.join(map(repr, self.args))})"


# ---------------------------------------------------------------------------
# eval helpers
# ---------------------------------------------------------------------------

def _as_float(data, ft: FieldType):
    k = phys_kind(ft)
    if k == K_DEC:
        return data.astype(np.float64) / float(POW10[ft.scale])
    if k == K_STR:
        out = np.zeros(len(data), dtype=np.float64)
        for i, b in enumerate(data):
            try:
                out[i] = float(b) if b else 0.0
            except ValueError:
                m = re.match(rb"\s*-?\d+(\.\d+)?", b)
                out[i] = float(m.group(0)) if m else 0.0
        return out
    return data.astype(np.float64)


def _as_decimal(data, ft: FieldType, to_scale: int):
    """-> scaled int64 at to_scale (object array of exact Python ints for
    wide decimals — precision > 18)."""
    k = phys_kind(ft)
    if k == K_DEC:
        diff = to_scale - ft.scale
        if getattr(data, "dtype", None) == object:
            if diff == 0:
                return data
            if diff > 0:
                return data * (10 ** diff)
            return _div_round(data, 10 ** (-diff))
        if diff == 0:
            return data.astype(np.int64)
        if diff > 0:
            # promote to exact bigints when the up-scaled value could pass
            # 18 digits (wide/narrow mixing makes this reachable)
            prec = ft.flen if ft.flen and ft.flen > 0 else 18
            if prec + diff > 18:
                return data.astype(np.int64).astype(object) * (10 ** diff)
            return data.astype(np.int64) * POW10[diff]
        return _div_round(data.astype(np.int64), POW10[-diff])
    if k == K_FLOAT:
        return np.round(data * POW10[to_scale]).astype(np.int64)
    if k == K_STR:
        f = _as_float(data, ft)
        return np.round(f * POW10[to_scale]).astype(np.int64)
    return data.astype(np.int64) * POW10[to_scale]


def _div_round(num, den):
    """Vectorized round-half-away-from-zero division (MySQL decimal
    rounding); exact bigint path for object (wide-decimal) arrays."""
    if getattr(num, "dtype", None) == object:
        d = abs(int(den)) if (np.isscalar(den) or
                              getattr(den, "shape", ()) == ()) else None
        if d is not None:
            d = d or 1
            neg = int(den) < 0
            sign = np.where((num < 0) != neg, -1, 1)
            q = (2 * np.abs(num) + d) // (2 * d)
            return sign * q
        den = den.astype(object)
        sign = np.where((num < 0) != (den < 0), -1, 1)
        a = np.abs(num)
        dd = np.abs(den)
        dd = np.where(dd == 0, 1, dd)
        return sign * ((2 * a + dd) // (2 * dd))
    num = num.astype(np.int64)
    if np.isscalar(den) or getattr(den, "shape", ()) == ():
        den = np.int64(den)
    sign = np.where((num < 0) != (den < 0), -1, 1)
    a = np.abs(num)
    d = np.abs(den)
    d_safe = np.where(d == 0, 1, d)
    q = (2 * a + d_safe) // (2 * d_safe)
    return sign * q


def _num_common(sf: ScalarFunc, chunk: Chunk):
    """Evaluate two args, coerce to a common numeric kind.
    -> (kind, lhs, rhs, nulls, scale)"""
    l, r = sf.args
    ld, ln = l.eval(chunk)
    rd, rn = r.eval(chunk)
    nulls = ln | rn
    lk, rk = phys_kind(l.ftype), phys_kind(r.ftype)
    # temporal vs string: parse the string as the temporal type (MySQL
    # compares a DATE column against '1995-04-01' as dates, not floats)
    if lk in (K_DATE,) or l.ftype.tp in (TYPE_DATETIME, TYPE_TIMESTAMP):
        if rk == K_STR:
            rd, extra_null = _cast_to(rd, rn, r.ftype, l.ftype)
            nulls = nulls | extra_null
            return _num_common_resume(l.ftype, l.ftype, ld, rd, nulls)
    if rk in (K_DATE,) or r.ftype.tp in (TYPE_DATETIME, TYPE_TIMESTAMP):
        if lk == K_STR:
            ld, extra_null = _cast_to(ld, ln, l.ftype, r.ftype)
            nulls = nulls | extra_null
            return _num_common_resume(r.ftype, r.ftype, ld, rd, nulls)
    # date/datetime mixing: promote DATE (days) to DATETIME (micros)
    if lk == K_DATE and r.ftype.tp in (TYPE_DATETIME, TYPE_TIMESTAMP):
        ld = ld.astype(np.int64) * 86_400_000_000
        lk = K_INT
    if rk == K_DATE and l.ftype.tp in (TYPE_DATETIME, TYPE_TIMESTAMP):
        rd = rd.astype(np.int64) * 86_400_000_000
        rk = K_INT
    if lk == K_DATE:
        lk = K_INT
    if rk == K_DATE:
        rk = K_INT
    if lk == K_STR and rk == K_STR:
        from ..utils.collate import ci_collation, sort_key_array
        coll = ci_collation(l.ftype, r.ftype)
        if coll is not None:
            return (K_STR, sort_key_array(ld, coll),
                    sort_key_array(rd, coll), nulls, 0)
        return K_STR, ld, rd, nulls, 0
    if K_FLOAT in (lk, rk) or K_STR in (lk, rk):
        return K_FLOAT, _as_float(ld, l.ftype), _as_float(rd, r.ftype), nulls, 0
    if K_DEC in (lk, rk):
        s = max(l.ftype.scale if lk == K_DEC else 0,
                r.ftype.scale if rk == K_DEC else 0)
        return K_DEC, _as_decimal(ld, l.ftype, s), _as_decimal(rd, r.ftype, s), nulls, s
    return K_INT, ld.astype(np.int64), rd.astype(np.int64), nulls, 0


def _num_common_resume(lft, rft, ld, rd, nulls):
    """Both sides now share a temporal type: compare as int64."""
    return K_INT, ld.astype(np.int64), rd.astype(np.int64), nulls, 0


def _bool_out(mask, nulls):
    return mask.astype(np.int64), nulls


# ---------------------------------------------------------------------------
# arithmetic
# ---------------------------------------------------------------------------

def _eval_add(sf, chunk):
    return _arith(sf, chunk, "add")


def _eval_sub(sf, chunk):
    return _arith(sf, chunk, "sub")


def _eval_mul(sf, chunk):
    return _arith(sf, chunk, "mul")


def _arith(sf, chunk, which):
    l, r = sf.args
    ld, ln = l.eval(chunk)
    rd, rn = r.eval(chunk)
    nulls = ln | rn
    out_ft = sf.ftype
    k = phys_kind(out_ft)
    if k == K_FLOAT:
        a = _as_float(ld, l.ftype)
        b = _as_float(rd, r.ftype)
        return {"add": a + b, "sub": a - b, "mul": a * b}[which], nulls
    if k == K_DEC:
        s = out_ft.scale
        if which == "mul":
            a = _as_decimal(ld, l.ftype, l.ftype.scale if phys_kind(l.ftype) == K_DEC else 0)
            b = _as_decimal(rd, r.ftype, r.ftype.scale if phys_kind(r.ftype) == K_DEC else 0)
            prod = a * b  # scale = s1 + s2 == out scale
            return prod, nulls
        a = _as_decimal(ld, l.ftype, s)
        b = _as_decimal(rd, r.ftype, s)
        return (a + b) if which == "add" else (a - b), nulls
    # ints (incl date arithmetic handled by date_add, not here)
    a = ld.astype(np.int64)
    b = rd.astype(np.int64)
    return {"add": a + b, "sub": a - b, "mul": a * b}[which], nulls


def _eval_div(sf, chunk):
    l, r = sf.args
    ld, ln = l.eval(chunk)
    rd, rn = r.eval(chunk)
    nulls = ln | rn
    out_ft = sf.ftype
    if phys_kind(out_ft) == K_FLOAT:
        a = _as_float(ld, l.ftype)
        b = _as_float(rd, r.ftype)
        zero = b == 0
        with np.errstate(divide="ignore", invalid="ignore"):
            res = np.where(zero, 0.0, a / np.where(zero, 1.0, b))
        return res, nulls | zero
    # decimal division: out scale = s1 + 4
    s1 = l.ftype.scale if phys_kind(l.ftype) == K_DEC else 0
    s2 = r.ftype.scale if phys_kind(r.ftype) == K_DEC else 0
    sr = out_ft.scale
    a = _as_decimal(ld, l.ftype, s1).astype(object)  # python ints: no overflow
    b = _as_decimal(rd, r.ftype, s2)
    zero = b == 0
    shift = POW10[sr + s2 - s1]
    num = a * shift
    den = np.where(zero, 1, b).astype(object)
    sign = np.where((num < 0) != (den < 0), -1, 1)
    q = (2 * np.abs(num) + den) // (2 * den)
    res = (sign * q)
    res = np.array([int(x) for x in res], dtype=np.int64)
    return res, nulls | zero


def _eval_intdiv(sf, chunk):
    kind, a, b, nulls, s = _num_common(sf, chunk)
    if kind == K_FLOAT:
        zero = b == 0
        with np.errstate(divide="ignore", invalid="ignore"):
            res = np.where(zero, 0, np.floor_divide(a, np.where(zero, 1.0, b)))
        return res.astype(np.int64), nulls | zero
    zero = b == 0
    bb = np.where(zero, 1, b)
    q = np.abs(a.astype(np.int64)) // np.abs(bb)
    res = np.where((a < 0) != (b < 0), -q, q)  # truncate toward zero (MySQL DIV)
    return res.astype(np.int64), nulls | zero


def _eval_mod(sf, chunk):
    kind, a, b, nulls, s = _num_common(sf, chunk)
    zero = b == 0
    bb = np.where(zero, 1, b)
    if kind == K_FLOAT:
        res = np.where(zero, 0.0, np.fmod(a, bb))
        return res, nulls | zero
    res = np.fmod(a.astype(np.int64), bb.astype(np.int64))
    return res, nulls | zero


def _eval_neg(sf, chunk):
    d, n = sf.args[0].eval(chunk)
    if phys_kind(sf.args[0].ftype) == K_STR:
        return -_as_float(d, sf.args[0].ftype), n
    return -d, n


# ---------------------------------------------------------------------------
# comparison / logic
# ---------------------------------------------------------------------------

_CMP = {
    "eq": lambda a, b: a == b, "ne": lambda a, b: a != b,
    "lt": lambda a, b: a < b, "le": lambda a, b: a <= b,
    "gt": lambda a, b: a > b, "ge": lambda a, b: a >= b,
}


def _make_cmp(name):
    def _f(sf, chunk):
        kind, a, b, nulls, _s = _num_common(sf, chunk)
        mask = _CMP[name](a, b)
        return _bool_out(mask & ~nulls, nulls)
    return _f


def _eval_nulleq(sf, chunk):  # <=>
    kind, a, b, nulls, _s = _num_common(sf, chunk)
    l, r = sf.args
    _ld, ln = l.eval(chunk)
    _rd, rn = r.eval(chunk)
    eq = (a == b) & ~ln & ~rn
    both_null = ln & rn
    return (eq | both_null).astype(np.int64), np.zeros(len(eq), dtype=bool)


def _eval_and(sf, chunk):
    ld, ln = sf.args[0].eval(chunk)
    rd, rn = sf.args[1].eval(chunk)
    lt = _truth(ld, sf.args[0].ftype)
    rt = _truth(rd, sf.args[1].ftype)
    lf = ~lt & ~ln
    rf = ~rt & ~rn
    res = lt & rt & ~ln & ~rn
    nulls = ~(lf | rf) & (ln | rn)  # false dominates null
    return res.astype(np.int64), nulls


def _eval_or(sf, chunk):
    ld, ln = sf.args[0].eval(chunk)
    rd, rn = sf.args[1].eval(chunk)
    lt = _truth(ld, sf.args[0].ftype) & ~ln
    rt = _truth(rd, sf.args[1].ftype) & ~rn
    res = lt | rt
    nulls = ~res & (ln | rn)  # true dominates null
    return res.astype(np.int64), nulls


def _eval_xor(sf, chunk):
    ld, ln = sf.args[0].eval(chunk)
    rd, rn = sf.args[1].eval(chunk)
    res = _truth(ld, sf.args[0].ftype) ^ _truth(rd, sf.args[1].ftype)
    nulls = ln | rn
    return res.astype(np.int64), nulls


def _eval_not(sf, chunk):
    d, n = sf.args[0].eval(chunk)
    return (~_truth(d, sf.args[0].ftype)).astype(np.int64), n


def _truth(data, ft: FieldType):
    k = phys_kind(ft)
    if k == K_STR:
        return _as_float(data, ft) != 0
    return data != 0


def _eval_isnull(sf, chunk):
    _d, n = sf.args[0].eval(chunk)
    return n.astype(np.int64), np.zeros(len(n), dtype=bool)


def _eval_istrue(sf, chunk):
    d, n = sf.args[0].eval(chunk)
    return (_truth(d, sf.args[0].ftype) & ~n).astype(np.int64), np.zeros(len(n), dtype=bool)


def _eval_isfalse(sf, chunk):
    d, n = sf.args[0].eval(chunk)
    return (~_truth(d, sf.args[0].ftype) & ~n).astype(np.int64), np.zeros(len(n), dtype=bool)


# -- IN: extra = None (args form) -------------------------------------------

def _eval_in(sf, chunk):
    target = sf.args[0]
    td, tn = target.eval(chunk)
    tk = phys_kind(target.ftype)
    any_null_item = False
    mask = np.zeros(len(td), dtype=bool)
    # coerce every item pairwise like a comparison
    for item in sf.args[1:]:
        pair = ScalarFunc("eq", [target, item], FieldType(tp=TYPE_LONGLONG))
        d, n = pair.eval(chunk)
        if isinstance(item, Constant) and item.value is None:
            any_null_item = True
        mask |= (d != 0) & ~n
    nulls = tn | (~mask & any_null_item)
    return mask.astype(np.int64), nulls


def _eval_in_set(sf, chunk):
    """IN with a prebuilt value set (subquery materialization).
    extra = (np.ndarray of values | set of bytes, contains_null: bool)."""
    target = sf.args[0]
    td, tn = target.eval(chunk)
    values, has_null = sf.extra
    k = phys_kind(target.ftype)
    if k == K_STR:
        mask = np.fromiter((b in values for b in td), dtype=bool, count=len(td))
    else:
        mask = np.isin(np.asarray(td), values)
    nulls = tn | (~mask & has_null)
    return mask.astype(np.int64), nulls


# -- LIKE -------------------------------------------------------------------

def like_to_regex(pattern: bytes, escape: bytes = b"\\") -> re.Pattern:
    out = [b"^"]
    i = 0
    esc = escape[:1]
    while i < len(pattern):
        c = pattern[i:i + 1]
        if c == esc and i + 1 < len(pattern):
            out.append(re.escape(pattern[i + 1:i + 2]))
            i += 2
            continue
        if c == b"%":
            out.append(b".*")
        elif c == b"_":
            out.append(b".")
        else:
            out.append(re.escape(c))
        i += 1
    out.append(b"$")
    # case sensitivity follows the collation (utf8mb4_bin default =
    # sensitive; _ci callers pass case-folded operands) — reference:
    # builtinLikeSig uses the collator, not an ignore-case matcher
    return re.compile(b"".join(out), re.DOTALL)


def _eval_like(sf, chunk):
    d, n = sf.args[0].eval(chunk)
    pat = sf.args[1]
    from ..utils.collate import is_ci, sort_key
    coll = sf.args[0].ftype.collate
    ci = is_ci(coll)
    if isinstance(pat, Constant) and sf.extra is not None and not ci:
        rx = sf.extra
        pd = None
        pn = np.zeros(len(d), dtype=bool)
    else:
        pd, pn = pat.eval(chunk)
        rx = None
    nulls = n | pn
    out = np.zeros(len(d), dtype=bool)
    if rx is not None:
        for i, b in enumerate(d):
            if not nulls[i]:
                out[i] = rx.match(b if isinstance(b, bytes) else str(b).encode()) is not None
    else:
        const_pat = isinstance(pat, Constant)
        if const_pat and len(d):
            # constant pattern: sort-key + compile ONCE, not per row
            p0 = sort_key(pd[0], coll) if ci else pd[0]
            rx0 = like_to_regex(p0)
        rx_cache: dict = {}  # compile once per distinct pattern, not per row
        for i, b in enumerate(d):
            if not nulls[i]:
                v = sort_key(b, coll) if ci else b
                if const_pat:
                    rx2 = rx0
                else:
                    p = sort_key(pd[i], coll) if ci else pd[i]
                    rx2 = rx_cache.get(p)
                    if rx2 is None:
                        rx2 = rx_cache[p] = like_to_regex(p)
                out[i] = rx2.match(v) is not None
    return out.astype(np.int64), nulls


def _eval_regexp(sf, chunk):
    d, n = sf.args[0].eval(chunk)
    pd, pn = sf.args[1].eval(chunk)
    nulls = n | pn
    out = np.zeros(len(d), dtype=bool)
    for i, b in enumerate(d):
        if not nulls[i]:
            out[i] = re.search(pd[i], b) is not None
    return out.astype(np.int64), nulls


# -- CASE / IF / COALESCE ---------------------------------------------------

def _cast_to(data, nulls, from_ft, to_ft):
    """Coerce evaluated (data,nulls) into to_ft's physical representation."""
    fk, tk = phys_kind(from_ft), phys_kind(to_ft)
    if from_ft.tp == TYPE_NULL:
        return _null_fill_array(to_ft, len(data)), nulls
    if tk == K_STR:
        if fk == K_STR:
            return data, nulls
        from ..sqltypes import format_value
        out = np.empty(len(data), dtype=object)
        for i in range(len(data)):
            s = format_value(data[i].item() if isinstance(data[i], np.generic) else data[i], from_ft)
            out[i] = (s or "").encode()
        return out, nulls
    if tk == K_FLOAT:
        return _as_float(data, from_ft), nulls
    if tk == K_DEC:
        return _as_decimal(data, from_ft, to_ft.scale), nulls
    if tk == K_DATE:
        if fk == K_DATE:
            return data.astype(np.int32), nulls
        if from_ft.tp in (TYPE_DATETIME, TYPE_TIMESTAMP):
            return (data // 86_400_000_000).astype(np.int32), nulls
        if fk == K_STR:
            from ..sqltypes import parse_date_str
            out = np.zeros(len(data), dtype=np.int32)
            bad = np.zeros(len(data), dtype=bool)
            for i, b in enumerate(data):
                if nulls[i]:
                    continue
                try:
                    out[i] = parse_date_str(b.decode())
                except Exception:
                    bad[i] = True
            return out, nulls | bad
        return data.astype(np.int32), nulls
    # K_INT targets
    if to_ft.tp in (TYPE_DATETIME, TYPE_TIMESTAMP) and fk == K_DATE:
        return data.astype(np.int64) * 86_400_000_000, nulls
    if to_ft.tp in (TYPE_DATETIME, TYPE_TIMESTAMP) and fk == K_STR:
        from ..sqltypes import parse_datetime_str
        out = np.zeros(len(data), dtype=np.int64)
        bad = np.zeros(len(data), dtype=bool)
        for i, b in enumerate(data):
            if nulls[i]:
                continue
            try:
                out[i] = parse_datetime_str(b.decode())
            except Exception:
                bad[i] = True
        return out, nulls | bad
    if fk == K_DEC:
        return _div_round(data, POW10[from_ft.scale]).astype(np.int64), nulls
    if fk == K_FLOAT:
        return np.round(data).astype(np.int64), nulls
    if fk == K_STR:
        return np.round(_as_float(data, from_ft)).astype(np.int64), nulls
    return data.astype(np.int64), nulls


def _eval_case(sf, chunk):
    """args: [cond1, res1, cond2, res2, ..., else?] (search form prebuilt)."""
    n_rows = chunk.num_rows
    args = sf.args
    has_else = len(args) % 2 == 1
    pairs = (len(args) - (1 if has_else else 0)) // 2
    out = _null_fill_array(sf.ftype, n_rows)
    out_nulls = np.ones(n_rows, dtype=bool)
    decided = np.zeros(n_rows, dtype=bool)
    for p in range(pairs):
        cd, cn = args[2 * p].eval(chunk)
        cond = _truth(cd, args[2 * p].ftype) & ~cn & ~decided
        if cond.any():
            rd, rn = args[2 * p + 1].eval(chunk)
            rd, rn = _cast_to(rd, rn, args[2 * p + 1].ftype, sf.ftype)
            out[cond] = rd[cond]
            out_nulls[cond] = rn[cond]
        decided |= cond
    if has_else:
        rest = ~decided
        if rest.any():
            rd, rn = args[-1].eval(chunk)
            rd, rn = _cast_to(rd, rn, args[-1].ftype, sf.ftype)
            out[rest] = rd[rest]
            out_nulls[rest] = rn[rest]
    return out, out_nulls


def _eval_if(sf, chunk):
    cond, a, b = sf.args
    return _eval_case(ScalarFunc("case", [cond, a, b], sf.ftype), chunk)


def _eval_coalesce(sf, chunk):
    n_rows = chunk.num_rows
    out = _null_fill_array(sf.ftype, n_rows)
    out_nulls = np.ones(n_rows, dtype=bool)
    remaining = np.ones(n_rows, dtype=bool)
    for a in sf.args:
        if not remaining.any():
            break
        d, n = a.eval(chunk)
        d, n = _cast_to(d, n, a.ftype, sf.ftype)
        take = remaining & ~n
        out[take] = d[take]
        out_nulls[take] = False
        remaining &= n
    return out, out_nulls


def _eval_nullif(sf, chunk):
    eq = ScalarFunc("eq", sf.args, FieldType(tp=TYPE_LONGLONG))
    d, n = eq.eval(chunk)
    vd, vn = sf.args[0].eval(chunk)
    iseq = (d != 0) & ~n
    return vd, vn | iseq


def _eval_cast(sf, chunk):
    d, n = sf.args[0].eval(chunk)
    return _cast_to(d, n, sf.args[0].ftype, sf.ftype)


# ---------------------------------------------------------------------------
# strings
# ---------------------------------------------------------------------------

def _str_args(sf, chunk):
    out = []
    nulls = None
    for a in sf.args:
        d, n = a.eval(chunk)
        d, n = _cast_to(d, n, a.ftype, FieldType(tp=TYPE_VARCHAR))
        out.append(d)
        nulls = n if nulls is None else (nulls | n)
    return out, nulls


def _eval_concat(sf, chunk):
    ds, nulls = _str_args(sf, chunk)
    n_rows = len(ds[0])
    out = np.empty(n_rows, dtype=object)
    for i in range(n_rows):
        out[i] = b"".join(d[i] for d in ds)
    return out, nulls


def _eval_concat_ws(sf, chunk):
    ds, _ = _str_args(sf, chunk)
    seps = ds[0]
    _d0, sep_null = sf.args[0].eval(chunk)
    n_rows = len(seps)
    out = np.empty(n_rows, dtype=object)
    # NULL args are skipped (not propagated) for concat_ws
    arg_nulls = [a.eval(chunk)[1] for a in sf.args[1:]]
    for i in range(n_rows):
        parts = [d[i] for j, d in enumerate(ds[1:]) if not arg_nulls[j][i]]
        out[i] = seps[i].join(parts)
    return out, sep_null


def _eval_upper(sf, chunk):
    ds, nulls = _str_args(sf, chunk)
    out = np.array([b.upper() for b in ds[0]], dtype=object)
    return out, nulls


def _eval_lower(sf, chunk):
    ds, nulls = _str_args(sf, chunk)
    out = np.array([b.lower() for b in ds[0]], dtype=object)
    return out, nulls


def _eval_length(sf, chunk):
    ds, nulls = _str_args(sf, chunk)
    return np.array([len(b) for b in ds[0]], dtype=np.int64), nulls


def _eval_char_length(sf, chunk):
    ds, nulls = _str_args(sf, chunk)
    return np.array([len(b.decode("utf-8", "replace")) for b in ds[0]],
                    dtype=np.int64), nulls


def _int_arg(sf_arg, chunk):
    d, n = sf_arg.eval(chunk)
    d, n = _cast_to(d, n, sf_arg.ftype, FieldType(tp=TYPE_LONGLONG))
    return d, n


def _eval_substring(sf, chunk):
    sd, sn = sf.args[0].eval(chunk)
    sd, sn = _cast_to(sd, sn, sf.args[0].ftype, FieldType(tp=TYPE_VARCHAR))
    pos, pn = _int_arg(sf.args[1], chunk)
    nulls = sn | pn
    if len(sf.args) > 2:
        ln, lnn = _int_arg(sf.args[2], chunk)
        nulls = nulls | lnn
    else:
        ln = None
    out = np.empty(len(sd), dtype=object)
    for i in range(len(sd)):
        s = sd[i]
        p = int(pos[i])
        if p > 0:
            start = p - 1
        elif p < 0:
            start = max(len(s) + p, 0)
        else:
            out[i] = b""
            continue
        if ln is not None:
            l = int(ln[i])
            out[i] = s[start:start + l] if l > 0 else b""
        else:
            out[i] = s[start:]
    return out, nulls


def _eval_trim(sf, chunk):
    # args: [str, direction-const, remstr?]
    sd, sn = sf.args[0].eval(chunk)
    sd, sn = _cast_to(sd, sn, sf.args[0].ftype, FieldType(tp=TYPE_VARCHAR))
    direction = sf.args[1].value if len(sf.args) > 1 else b"both"
    if isinstance(direction, bytes):
        direction = direction.decode()
    rem = b" "
    rem_nulls = None
    if len(sf.args) > 2:
        rd, rem_nulls = sf.args[2].eval(chunk)
        rem = None
    out = np.empty(len(sd), dtype=object)
    for i in range(len(sd)):
        s = sd[i]
        r = rem if rem is not None else rd[i]
        if direction in ("both", "leading"):
            while s.startswith(r) and r:
                s = s[len(r):]
        if direction in ("both", "trailing"):
            while s.endswith(r) and r:
                s = s[:-len(r)]
        out[i] = s
    nulls = sn if rem_nulls is None else (sn | rem_nulls)
    return out, nulls


def _eval_ltrim(sf, chunk):
    ds, nulls = _str_args(sf, chunk)
    return np.array([b.lstrip(b" ") for b in ds[0]], dtype=object), nulls


def _eval_rtrim(sf, chunk):
    ds, nulls = _str_args(sf, chunk)
    return np.array([b.rstrip(b" ") for b in ds[0]], dtype=object), nulls


def _eval_replace(sf, chunk):
    ds, nulls = _str_args(sf, chunk)
    out = np.array([a.replace(b, c) for a, b, c in zip(*ds)], dtype=object)
    return out, nulls


def _eval_locate(sf, chunk):
    ds, nulls = _str_args(sf, chunk)
    return np.array([h.find(nd) + 1 for nd, h in zip(ds[0], ds[1])],
                    dtype=np.int64), nulls


def _eval_left(sf, chunk):
    sd, sn = sf.args[0].eval(chunk)
    sd, sn = _cast_to(sd, sn, sf.args[0].ftype, FieldType(tp=TYPE_VARCHAR))
    nd, nn = _int_arg(sf.args[1], chunk)
    out = np.array([s[:max(int(k), 0)] for s, k in zip(sd, nd)], dtype=object)
    return out, sn | nn


def _eval_right(sf, chunk):
    sd, sn = sf.args[0].eval(chunk)
    sd, sn = _cast_to(sd, sn, sf.args[0].ftype, FieldType(tp=TYPE_VARCHAR))
    nd, nn = _int_arg(sf.args[1], chunk)
    out = np.array([s[-int(k):] if int(k) > 0 else b"" for s, k in zip(sd, nd)],
                   dtype=object)
    return out, sn | nn


def _eval_reverse(sf, chunk):
    ds, nulls = _str_args(sf, chunk)
    return np.array([b[::-1] for b in ds[0]], dtype=object), nulls


def _eval_repeat(sf, chunk):
    sd, sn = sf.args[0].eval(chunk)
    sd, sn = _cast_to(sd, sn, sf.args[0].ftype, FieldType(tp=TYPE_VARCHAR))
    nd, nn = _int_arg(sf.args[1], chunk)
    out = np.array([s * max(int(k), 0) for s, k in zip(sd, nd)], dtype=object)
    return out, sn | nn


def _eval_lpad(sf, chunk):
    ds, nulls = _str_args(sf, chunk)
    nd, nn = _int_arg(sf.args[1], chunk)
    out = np.empty(len(ds[0]), dtype=object)
    for i in range(len(ds[0])):
        s, total, pad = ds[0][i], int(nd[i]), ds[2][i]
        if total <= len(s):
            out[i] = s[:total]
        elif pad:
            need = total - len(s)
            out[i] = (pad * (need // len(pad) + 1))[:need] + s
        else:
            out[i] = b"" if total > len(s) else s[:total]
    return out, nulls | nn


# ---------------------------------------------------------------------------
# date/time
# ---------------------------------------------------------------------------

def _to_dateparts(sf_arg, chunk):
    """-> (list of datetime.date/datetime or None)."""
    d, n = sf_arg.eval(chunk)
    ft = sf_arg.ftype
    k = phys_kind(ft)
    out = []
    if k == K_DATE:
        for i in range(len(d)):
            out.append(None if n[i] else days_to_date(int(d[i])))
    elif ft.tp in (TYPE_DATETIME, TYPE_TIMESTAMP):
        for i in range(len(d)):
            out.append(None if n[i] else micros_to_datetime(int(d[i])))
    elif k == K_STR:
        from ..sqltypes import parse_datetime_str
        for i in range(len(d)):
            if n[i]:
                out.append(None)
            else:
                try:
                    out.append(micros_to_datetime(parse_datetime_str(d[i].decode())))
                except Exception:
                    out.append(None)
    else:
        for i in range(len(d)):
            out.append(None)
    return out


def _date_part(fn):
    def _f(sf, chunk):
        parts = _to_dateparts(sf.args[0], chunk)
        out = np.zeros(len(parts), dtype=np.int64)
        nulls = np.zeros(len(parts), dtype=bool)
        for i, p in enumerate(parts):
            if p is None:
                nulls[i] = True
            else:
                out[i] = fn(p)
        return out, nulls
    return _f


_EXTRACT_FNS = {
    "year": lambda p: p.year,
    "month": lambda p: p.month,
    "day": lambda p: p.day,
    "hour": lambda p: getattr(p, "hour", 0),
    "minute": lambda p: getattr(p, "minute", 0),
    "second": lambda p: getattr(p, "second", 0),
    "microsecond": lambda p: getattr(p, "microsecond", 0),
    "quarter": lambda p: (p.month - 1) // 3 + 1,
    "week": lambda p: p.isocalendar()[1],
    "year_month": lambda p: p.year * 100 + p.month,
}


def _eval_extract(sf, chunk):
    unit = sf.extra
    fn = _EXTRACT_FNS.get(unit)
    if fn is None:
        raise TiDBError(f"unsupported EXTRACT unit {unit}")
    return _date_part(fn)(ScalarFunc(sf.op, [sf.args[1]], sf.ftype), chunk)


_UNIT_TO_US = {
    "microsecond": 1, "second": 1_000_000, "minute": 60_000_000,
    "hour": 3_600_000_000, "day": 86_400_000_000, "week": 7 * 86_400_000_000,
}


def _eval_date_arith(sf, chunk):
    """date_add/date_sub. args=[date_expr, interval_value]; extra=(unit, sign)."""
    unit, sign = sf.extra
    vd, vn = _int_arg(sf.args[1], chunk)
    delta = vd.astype(np.int64) * sign
    src = sf.args[0]
    out_ft = sf.ftype
    if unit in _UNIT_TO_US:
        if phys_kind(out_ft) == K_DATE:
            dd, dn = src.eval(chunk)
            dd, dn = _cast_to(dd, dn, src.ftype, FieldType(tp=TYPE_DATE))
            return (dd.astype(np.int64) + delta * _UNIT_TO_US[unit] // 86_400_000_000).astype(np.int32), dn | vn
        dd, dn = src.eval(chunk)
        dd, dn = _cast_to(dd, dn, src.ftype, FieldType(tp=TYPE_DATETIME))
        return dd + delta * _UNIT_TO_US[unit], dn | vn
    # month/quarter/year arithmetic needs calendars
    parts = _to_dateparts(src, chunk)
    months = {"month": 1, "quarter": 3, "year": 12}[unit]
    out_is_date = phys_kind(out_ft) == K_DATE
    out = np.zeros(len(parts), dtype=np.int32 if out_is_date else np.int64)
    nulls = vn.copy()
    import datetime as _dt
    from ..sqltypes import date_to_days, datetime_to_micros
    for i, p in enumerate(parts):
        if p is None:
            nulls[i] = True
            continue
        total = p.year * 12 + (p.month - 1) + int(delta[i]) * months
        y, m = divmod(total, 12)
        m += 1
        day = min(p.day, _days_in_month(y, m))
        if out_is_date:
            out[i] = date_to_days(y, m, day)
        else:
            hh = getattr(p, "hour", 0)
            mm = getattr(p, "minute", 0)
            ss = getattr(p, "second", 0)
            us = getattr(p, "microsecond", 0)
            out[i] = datetime_to_micros(_dt.datetime(y, m, day, hh, mm, ss, us))
    return out, nulls


def _days_in_month(y, m):
    import calendar
    return calendar.monthrange(y, m)[1]


def _eval_datediff(sf, chunk):
    a = ScalarFunc("cast", [sf.args[0]], FieldType(tp=TYPE_DATE))
    b = ScalarFunc("cast", [sf.args[1]], FieldType(tp=TYPE_DATE))
    ad, an = a.eval(chunk)
    bd, bn = b.eval(chunk)
    return (ad.astype(np.int64) - bd.astype(np.int64)), an | bn


def _eval_date(sf, chunk):
    return _eval_cast(ScalarFunc("cast", sf.args, FieldType(tp=TYPE_DATE)), chunk)


def _eval_date_format(sf, chunk):
    parts = _to_dateparts(sf.args[0], chunk)
    fd, fn_ = sf.args[1].eval(chunk)
    out = np.empty(len(parts), dtype=object)
    nulls = fn_.copy()
    for i, p in enumerate(parts):
        if p is None or nulls[i]:
            out[i] = b""
            nulls[i] = True
            continue
        out[i] = _mysql_date_format(p, fd[i].decode())
    return out, nulls


_FMT_MAP = {
    "Y": "%Y", "y": "%y", "m": "%m", "d": "%d", "H": "%H", "i": "%M",
    "s": "%S", "S": "%S", "f": "%f", "M": "%B", "b": "%b", "W": "%A",
    "a": "%a", "j": "%j", "T": "%H:%M:%S", "e": "%d",
}


def _mysql_date_format(p, fmt: str) -> bytes:
    out = []
    i = 0
    while i < len(fmt):
        c = fmt[i]
        if c == "%" and i + 1 < len(fmt):
            spec = fmt[i + 1]
            if spec in _FMT_MAP:
                out.append(p.strftime(_FMT_MAP[spec]))
            elif spec == "%":
                out.append("%")
            else:
                out.append(spec)
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out).encode()


# ---------------------------------------------------------------------------
# math
# ---------------------------------------------------------------------------

def _eval_abs(sf, chunk):
    d, n = sf.args[0].eval(chunk)
    return np.abs(d), n


def _float_fn(fn):
    def _f(sf, chunk):
        d, n = sf.args[0].eval(chunk)
        f = _as_float(d, sf.args[0].ftype)
        with np.errstate(all="ignore"):
            res = fn(f)
        bad = ~np.isfinite(res)
        return np.where(bad, 0.0, res), n | bad
    return _f


def _eval_round(sf, chunk):
    src = sf.args[0]
    nd = 0
    if len(sf.args) > 1:
        c = sf.args[1]
        nd = int(c.value) if isinstance(c, Constant) else 0
    d, n = src.eval(chunk)
    k = phys_kind(src.ftype)
    if k == K_DEC:
        s = src.ftype.scale
        if nd >= s:
            return d, n
        scaled = _div_round(d, POW10[s - nd])
        if phys_kind(sf.ftype) == K_DEC and sf.ftype.scale == nd:
            return scaled, n
        return scaled * POW10[sf.ftype.scale - nd] if phys_kind(sf.ftype) == K_DEC else scaled, n
    if k == K_FLOAT:
        return np.round(d, nd), n
    if nd >= 0:
        return d, n
    p = POW10[-nd]
    return _div_round(d, p) * p, n


def _eval_truncate(sf, chunk):
    """TRUNCATE(x, d): drop digits past position d toward zero. Decimal
    inputs stay exact scaled-int decimals (reference:
    expression/builtin_math.go truncate keeps the decimal type); float
    inputs go through exact decimal scaling to avoid binary-float digit
    drift (trunc(0.29*100) is 28 in pure float64)."""
    src = sf.args[0]
    d, n = src.eval(chunk)
    k = phys_kind(src.ftype)
    if len(sf.args) > 1 and not isinstance(sf.args[1], Constant):
        # column-valued digit count: per-row exact truncation; the result
        # type is DOUBLE (no static scale exists — builder contract)
        nd_d, nd_n = sf.args[1].eval(chunk)
        from decimal import Decimal, ROUND_DOWN
        s = src.ftype.scale if k == K_DEC else 0

        from decimal import localcontext

        def one(v, places):
            places = max(min(int(places), 60), -60)
            if k == K_DEC:
                dec = Decimal(int(v)).scaleb(-s)
            elif k == K_FLOAT:
                if not np.isfinite(v):
                    return float(v)
                dec = Decimal(repr(float(v)))
            else:
                dec = Decimal(int(v))
            with localcontext() as lctx:
                lctx.prec = 400  # int digits + 60 kept places, with room
                q = dec.quantize(Decimal(1).scaleb(-places),
                                 rounding=ROUND_DOWN)
            return float(q)
        out = np.array([one(v, p) if not (bool(nn) or bool(vn)) else 0.0
                        for v, p, vn, nn in zip(d, nd_d, n, nd_n)],
                       dtype=np.float64)
        return out, n | nd_n
    if (len(sf.args) > 1 and isinstance(sf.args[1], Constant)
            and sf.args[1].value is None):
        return d, np.ones_like(n)  # TRUNCATE(x, NULL) is NULL
    # MySQL clamps the digit count (TRUNCATE(x, 2000000) is a no-op,
    # TRUNCATE(x, -2000000) is 0); without the clamp Decimal.scaleb
    # overflows its context and p10() computes astronomically wide ints
    nd = max(min(int(sf.args[1].value), 60), -60) if len(sf.args) > 1 else 0
    if k not in (K_DEC, K_FLOAT) and (d.dtype == object
                                      or not np.issubdtype(d.dtype,
                                                           np.integer)):
        # string (or other coercible) input: MySQL truncates the numeric
        # value and returns a double
        d = _as_float(d, src.ftype)
        k = K_FLOAT

    def p10(e):  # exact power; POW10 covers the decimal domain, int past it
        return POW10[e] if e < len(POW10) else 10 ** e

    _I64MAX = np.iinfo(np.int64).max

    def trunc_div(a, p):
        if a.dtype != object and p > _I64MAX:
            return np.zeros_like(a)  # |a| < p always: quotient is 0
        return np.where(a >= 0, a // p, -((-a) // p))

    def rescale(q, e):
        p = p10(e)
        if q.dtype != object and p > _I64MAX:
            return np.zeros_like(q)  # q is already all-zero here
        return q * p

    if k == K_DEC:
        s = src.ftype.scale
        if nd >= s:
            return d, n
        p = p10(s - nd) if nd >= 0 else p10(s) * p10(-nd)
        q = trunc_div(d, p)
        if nd < 0:
            return rescale(q, -nd), n  # output scale 0
        out_s = sf.ftype.scale if phys_kind(sf.ftype) == K_DEC else nd
        return (rescale(q, out_s - nd) if out_s > nd else q), n
    if k == K_FLOAT:
        from decimal import Decimal, ROUND_DOWN, localcontext
        qd = Decimal(1).scaleb(-nd)
        with localcontext() as lctx:
            # float64 spans ~±1e308 with up to 60 kept fraction digits:
            # the default 28-digit context would raise InvalidOperation
            lctx.prec = 400
            out = np.array([
                float(Decimal(repr(float(v))).quantize(qd,
                                                       rounding=ROUND_DOWN))
                if np.isfinite(v) else float(v)
                for v in np.asarray(d, dtype=np.float64)], dtype=np.float64)
        return out, n
    if nd >= 0:
        return d, n
    p = p10(-nd)
    q = trunc_div(d, p)
    return (q * p if p <= _I64MAX else q), n


def _eval_ceil(sf, chunk):
    d, n = sf.args[0].eval(chunk)
    k = phys_kind(sf.args[0].ftype)
    if k == K_DEC:
        s = sf.args[0].ftype.scale
        p = POW10[s]
        return -((-d) // p), n
    if k == K_FLOAT:
        return np.ceil(d).astype(np.int64), n
    return d.astype(np.int64), n


def _eval_floor(sf, chunk):
    d, n = sf.args[0].eval(chunk)
    k = phys_kind(sf.args[0].ftype)
    if k == K_DEC:
        p = POW10[sf.args[0].ftype.scale]
        return d // p, n
    if k == K_FLOAT:
        return np.floor(d).astype(np.int64), n
    return d.astype(np.int64), n


def _eval_sign(sf, chunk):
    d, n = sf.args[0].eval(chunk)
    f = _as_float(d, sf.args[0].ftype)
    return np.sign(f).astype(np.int64), n


def _eval_pow(sf, chunk):
    kind, a, b, nulls, _ = _num_common(sf, chunk)
    af = a.astype(np.float64) if kind != K_FLOAT else a
    bf = b.astype(np.float64) if kind != K_FLOAT else b
    with np.errstate(all="ignore"):
        res = np.power(af, bf)
    return res, nulls


def _int_binop(fn):
    def _f(sf, chunk):
        kind, a, b, nulls, _s = _num_common(sf, chunk)
        ai = a.astype(np.int64) if kind != K_FLOAT else np.round(a).astype(np.int64)
        bi = b.astype(np.int64) if kind != K_FLOAT else np.round(b).astype(np.int64)
        return fn(ai, bi), nulls
    return _f


def _eval_bitneg(sf, chunk):
    d, n = sf.args[0].eval(chunk)
    return ~d.astype(np.int64), n


def _eval_greatest(sf, chunk):
    return _minmax(sf, chunk, np.maximum)


def _eval_least(sf, chunk):
    return _minmax(sf, chunk, np.minimum)


def _minmax(sf, chunk, fn):
    acc = None
    nulls = None
    for a in sf.args:
        d, n = a.eval(chunk)
        d, n = _cast_to(d, n, a.ftype, sf.ftype)
        acc = d if acc is None else fn(acc, d)
        nulls = n if nulls is None else (nulls | n)
    return acc, nulls


# ---------------------------------------------------------------------------
# dispatch table
# ---------------------------------------------------------------------------

_DISPATCH = {
    "add": _eval_add, "sub": _eval_sub, "mul": _eval_mul, "div": _eval_div,
    "intdiv": _eval_intdiv, "mod": _eval_mod, "neg": _eval_neg,
    "eq": _make_cmp("eq"), "ne": _make_cmp("ne"), "lt": _make_cmp("lt"),
    "le": _make_cmp("le"), "gt": _make_cmp("gt"), "ge": _make_cmp("ge"),
    "nulleq": _eval_nulleq,
    "and": _eval_and, "or": _eval_or, "xor": _eval_xor, "not": _eval_not,
    "isnull": _eval_isnull, "istrue": _eval_istrue, "isfalse": _eval_isfalse,
    "in": _eval_in, "in_set": _eval_in_set,
    "like": _eval_like, "regexp": _eval_regexp,
    "case": _eval_case, "if": _eval_if, "coalesce": _eval_coalesce,
    "ifnull": _eval_coalesce, "nullif": _eval_nullif, "cast": _eval_cast,
    "concat": _eval_concat, "concat_ws": _eval_concat_ws,
    "upper": _eval_upper, "lower": _eval_lower,
    "length": _eval_length, "char_length": _eval_char_length,
    "substring": _eval_substring, "trim": _eval_trim,
    "ltrim": _eval_ltrim, "rtrim": _eval_rtrim,
    "replace": _eval_replace, "locate": _eval_locate,
    "left": _eval_left, "right": _eval_right, "reverse": _eval_reverse,
    "repeat": _eval_repeat, "lpad": _eval_lpad,
    "year": _date_part(_EXTRACT_FNS["year"]),
    "month": _date_part(_EXTRACT_FNS["month"]),
    "dayofmonth": _date_part(_EXTRACT_FNS["day"]),
    "day": _date_part(_EXTRACT_FNS["day"]),
    "hour": _date_part(_EXTRACT_FNS["hour"]),
    "minute": _date_part(_EXTRACT_FNS["minute"]),
    "second": _date_part(_EXTRACT_FNS["second"]),
    "quarter": _date_part(_EXTRACT_FNS["quarter"]),
    "week": _date_part(_EXTRACT_FNS["week"]),
    "dayofweek": _date_part(lambda p: p.isoweekday() % 7 + 1),
    "dayofyear": _date_part(lambda p: p.timetuple().tm_yday),
    "extract": _eval_extract,
    "date_arith": _eval_date_arith,
    "datediff": _eval_datediff, "date": _eval_date,
    "date_format": _eval_date_format,
    "abs": _eval_abs, "round": _eval_round, "truncate": _eval_truncate,
    "ceil": _eval_ceil,
    "floor": _eval_floor, "sign": _eval_sign, "pow": _eval_pow,
    "sqrt": _float_fn(np.sqrt), "exp": _float_fn(np.exp),
    "ln": _float_fn(np.log), "log2": _float_fn(np.log2),
    "log10": _float_fn(np.log10),
    "greatest": _eval_greatest, "least": _eval_least,
    "bitand": _int_binop(lambda a, b: a & b),
    "bitor": _int_binop(lambda a, b: a | b),
    "bitxor": _int_binop(lambda a, b: a ^ b),
    "shl": _int_binop(lambda a, b: a << np.clip(b, 0, 63)),
    "shr": _int_binop(lambda a, b: a >> np.clip(b, 0, 63)),
    "bitneg": _eval_bitneg,
}


def supported_scalar_ops():
    return set(_DISPATCH)


# extended builtin library registers itself into _DISPATCH (import must stay
# at the bottom: builtins_ext pulls helpers defined above)
from . import builtins_ext as _builtins_ext  # noqa: E402,F401
